"""Elastic tests: driver logic with mocked discovery (reference analog:
test/single/test_elastic_driver.py — simulated host add/remove, rank
stability, blacklisting) and a real fake-cluster integration run on
localhost (reference analog: test/integration/elastic_common.py:34-118 —
a discovery script whose output changes over time + scripted failures)."""

import os
import stat
import subprocess
import sys
import textwrap
import time

import pytest

from horovod_tpu.core import core_available
from horovod_tpu.runner.elastic.discovery import (FixedHosts, HostDiscovery,
                                                  HostManager)
from horovod_tpu.runner.elastic.registration import (FAILURE, SUCCESS,
                                                     WorkerStateRegistry)
from horovod_tpu.runner.hosts import HostInfo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class MockDiscovery(HostDiscovery):
    """Scripted sequence of host sets (reference analog: the elastic tests'
    fake discovery)."""

    def __init__(self, sequences):
        self._sequences = sequences
        self._idx = 0

    def find_available_hosts_and_slots(self):
        seq = self._sequences[min(self._idx, len(self._sequences) - 1)]
        self._idx += 1
        return dict(seq)


def test_host_manager_change_detection():
    disc = MockDiscovery([{"a": 2}, {"a": 2}, {"a": 2, "b": 2}, {"b": 2}])
    hm = HostManager(disc)
    assert hm.update_available_hosts() is True       # initial
    assert hm.update_available_hosts() is False      # no change
    assert hm.update_available_hosts() is True       # b added
    # rank stability: 'a' keeps its position while it exists
    assert [h.hostname for h in hm.current_hosts()] == ["a", "b"]
    assert hm.update_available_hosts() is True       # a removed
    assert [h.hostname for h in hm.current_hosts()] == ["b"]


def test_host_manager_blacklist():
    disc = MockDiscovery([{"a": 2, "b": 2}])
    hm = HostManager(disc)
    hm.blacklist("b")
    hm.update_available_hosts()
    assert [h.hostname for h in hm.current_hosts()] == ["a"]
    assert hm.slot_count() == 2


def test_host_manager_undrain_restores_capacity():
    """The driver reverts a drain reservation when no viable planned
    world exists (fall back to reactive recovery): the doomed host must
    stay usable until it actually dies."""
    disc = MockDiscovery([{"a": 2, "b": 2}])
    hm = HostManager(disc)
    hm.update_available_hosts()
    hm.drain("b", 2, cooldown_s=60.0)
    assert hm.slot_count() == 2          # reservation applied inline
    hm.undrain("b", 2)
    assert hm.slot_count() == 4          # capacity restored inline
    hm.update_available_hosts()
    assert hm.slot_count() == 4          # and across a refresh


def test_worker_state_registry():
    reg = WorkerStateRegistry(reset_limit=2)
    reg.reset(2)
    reg.record(0, "a", SUCCESS)
    reg.record(1, "b", FAILURE)
    assert reg.count(SUCCESS) == 1
    assert reg.count(FAILURE) == 1
    assert reg.failed_hosts() == {"b": 1}
    assert not reg.reset_limit_reached()
    reg.reset(2)
    reg.reset(2)
    assert reg.reset_limit_reached()


def test_object_state_commit_restore(hvd, tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    from horovod_tpu.elastic import ObjectState
    st = ObjectState(name="t1", epoch=0, w=[1.0, 2.0])
    st.epoch = 5
    st.w = [9.0, 9.0]
    st.commit()
    st.epoch = 7
    st.restore()
    assert st.epoch == 5 and st.w == [9.0, 9.0]
    # a fresh process (new State object) resumes from the committed file
    st2 = ObjectState(name="t1", epoch=0, w=[0.0])
    assert st2.epoch == 5 and st2.w == [9.0, 9.0]


def test_elastic_run_decorator_retries(hvd, tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    from horovod_tpu import elastic

    state = elastic.ObjectState(name="t2", count=0)
    attempts = []

    @elastic.run
    def train(state):
        attempts.append(1)
        if len(attempts) < 3:
            raise elastic.HorovodInternalError("simulated collective fail")
        return state.count

    assert train(state) == 0
    assert len(attempts) == 3


needs_core = pytest.mark.skipif(not core_available(),
                                reason="libhvdcore.so not built")


@needs_core
def test_push_notification_channel(monkeypatch):
    """Driver-push path in isolation (reference analog:
    WorkerNotificationService, ``runner/elastic/worker.py:46+``): the
    worker listener registers itself in the driver KV; a signed doc pushed
    to the listener is seen WITHOUT polling the driver; a forged doc is
    ignored; check_host_updates raises HostsUpdatedInterrupt."""
    import json
    from horovod_tpu import elastic
    from horovod_tpu.elastic import notification
    from horovod_tpu.runner.http_kv import KVStoreServer, kv_put

    driver_kv = KVStoreServer()
    driver_kv.start()
    secret = b"s" * 16
    monkeypatch.setenv("HVD_ELASTIC_KV", f"127.0.0.1:{driver_kv.port}")
    monkeypatch.setenv("HVD_ELASTIC_SECRET", secret.hex())
    monkeypatch.setenv("HVD_ELASTIC_GENERATION", "0")
    monkeypatch.setenv("HOROVOD_HOSTNAME", "127.0.0.1")
    monkeypatch.setenv("HOROVOD_RANK", "0")
    monkeypatch.setattr(elastic, "_current_generation", None)
    notification.reset_listener()
    try:

        class S(elastic.State):
            def save(self):
                pass

        # the mid-step probe NEVER pays listener setup: before any commit
        # it just reports nothing pending and registers nothing
        assert elastic.has_pending_update() is False
        assert driver_kv.scope("notify") == {}
        # the commit path starts + registers the listener
        S().check_host_updates()
        reg = driver_kv.scope("notify")
        assert "0" in reg, reg
        host, _, port = reg["0"].decode().rpartition(":")

        # hostile/malformed bytes on the open listener port must be
        # IGNORED, never crash the worker: non-dict JSON, non-string sig,
        # non-numeric generation, bad signature
        for junk in (b"[1, 2]", b"not json", b'{"generation": 1, "sig": 5}',
                     b'{"generation": "x"}',
                     json.dumps({"generation": 1,
                                 "sig": "not-a-real-signature"}).encode()):
            kv_put(host, int(port), "world", "current", junk)
            assert elastic.has_pending_update() is False, junk

        # the real signed doc is seen without any driver poll
        doc = {"generation": 1, "size": 2, "coord_addr": "127.0.0.1",
               "coord_port": 1234, "slots": {}}
        doc["sig"] = elastic.world_doc_signature(secret, doc)
        kv_put(host, int(port), "world", "current",
               json.dumps(doc).encode())
        assert elastic.has_pending_update() is True

        with pytest.raises(elastic.HostsUpdatedInterrupt) as ei:
            S().check_host_updates()
        assert ei.value.update["generation"] == 1
    finally:
        notification.reset_listener()
        driver_kv.stop()
        elastic._current_generation = None


@pytest.mark.skipif(not core_available(),
                    reason="libhvdcore.so not built")
def test_growth_notice_arrives_mid_step_via_push(tmp_path):
    """VERDICT r3 missing #2 'done' condition: a worker sleeping inside a
    long step (NOT committing) receives the growth notice via the push
    channel before its next commit — growth-response latency is no longer
    the commit interval."""
    disco = tmp_path / "discover.sh"
    disco.write_text(
        "#!/bin/bash\n"
        f"if [ -f {tmp_path}/grow ]; then echo localhost:3; "
        "else echo localhost:2; fi\n")
    disco.chmod(disco.stat().st_mode | stat.S_IEXEC)
    notice_log = tmp_path / "notices.log"

    prog = tmp_path / "train.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu import elastic

        hvd.init()
        state = elastic.ObjectState(name="push", step=0)

        @elastic.run
        def train(state):
            first_world = hvd.size() == 2
            state.commit()  # registers the push listener with the driver
            if first_world and hvd.rank() == 0:
                open(os.path.join({str(tmp_path)!r}, "grow"), "w").close()
            if first_world:
                # the "long step": no commits; only the pushed doc can
                # reach us here
                deadline = time.monotonic() + 60
                while not elastic.has_pending_update():
                    if time.monotonic() > deadline:
                        raise RuntimeError("push never arrived")
                    time.sleep(0.1)
                with open({str(notice_log)!r}, "a") as f:
                    f.write(f"NOTICED rank={{hvd.rank()}} before commit\\n")
                state.commit()  # now raises HostsUpdatedInterrupt
                raise RuntimeError("commit did not raise after push")
            out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                                name=f"w{{hvd.size()}}")
            assert float(np.asarray(out)[0]) == 3.0
            return hvd.rank()

        train(state)
        print("done", hvd.rank(), flush=True)
        hvd.shutdown()
    """))

    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
    driver = ElasticDriver(
        HostDiscoveryScript(str(disco)), [sys.executable, str(prog)],
        min_np=2, max_np=3, reset_limit=3, ckpt_dir=str(tmp_path))
    rc = driver.run()
    assert rc == 0
    notices = notice_log.read_text().strip().splitlines()
    # both generation-0 survivors learned of growth mid-step, pre-commit
    assert len(notices) == 2, notices


def test_elastic_integration_fake_cluster(tmp_path):
    """Real elastic run on localhost: the discovery script's output changes
    with an epoch file, worker of generation 0 fails once, generation 1
    succeeds resuming from committed state (reference analog:
    test/integration/elastic_common.py scripted discovery + exit)."""
    epoch_file = tmp_path / "epoch"
    epoch_file.write_text("0")
    disco = tmp_path / "discover.sh"
    disco.write_text("#!/bin/bash\necho localhost:2\n")
    disco.chmod(disco.stat().st_mode | stat.S_IEXEC)

    prog = tmp_path / "train.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {REPO!r})
        import numpy as np
        from horovod_tpu.core.core_backend import CoreBackend
        from horovod_tpu.ops.reduce_op import ReduceOp
        from horovod_tpu import elastic

        be = CoreBackend()
        state = elastic.ObjectState(name="itg", step=0)
        gen = int(os.environ.get("HVD_ELASTIC_GENERATION", 0))
        # first generation: rank 1 crashes at step 2 after committing step 1
        for step in range(state.step, 5):
            out = be.allreduce_async(f"s{{step}}",
                                     np.ones(4, np.float32),
                                     ReduceOp.SUM).wait(30)
            state.step = step + 1
            state.save()
            if gen == 0 and be.rank == 1 and step == 1:
                os._exit(17)
        print(f"rank {{be.rank}} gen {{gen}} finished at step "
              f"{{state.step}}", flush=True)
        assert state.step == 5
        be.shutdown()
    """))

    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
    driver = ElasticDriver(
        HostDiscoveryScript(str(disco)), [sys.executable, str(prog)],
        min_np=2, max_np=2, reset_limit=3, ckpt_dir=str(tmp_path))
    rc = driver.run()
    assert rc == 0


@pytest.mark.skipif(not core_available(),
                    reason="libhvdcore.so not built")
def test_elastic_growth_does_not_restart_survivors(tmp_path):
    """Scale-up extends the running generation (VERDICT r1 #6): the
    discovery output grows 2 -> 3 slots mid-run; survivors pick the new
    world up at commit() via HostsUpdatedInterrupt and re-init IN PLACE
    (each rank boots exactly once), the new worker joins, and a
    3-rank collective completes."""
    boot_log = tmp_path / "boots.log"
    disco = tmp_path / "discover.sh"
    # discovery reports 2 slots until the grow-marker appears
    disco.write_text(
        "#!/bin/bash\n"
        f"if [ -f {tmp_path}/grow ]; then echo localhost:3; "
        "else echo localhost:2; fi\n")
    disco.chmod(disco.stat().st_mode | stat.S_IEXEC)

    prog = tmp_path / "train.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu import elastic

        hvd.init()
        with open({str(boot_log)!r}, "a") as f:
            f.write(f"BOOT rank={{hvd.rank()}} pid={{os.getpid()}}\\n")
        if hvd.rank() == 0 and hvd.size() == 2:
            open(os.path.join({str(tmp_path)!r}, "grow"), "w").close()

        state = elastic.ObjectState(name="grow", step=0)

        @elastic.run
        def train(state):
            while True:
                out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                                    name=f"g{{hvd.size()}}.{{state.step}}")
                state.step += 1
                time.sleep(0.3)
                state.commit()   # raises HostsUpdatedInterrupt on growth
                if hvd.size() >= 3 and float(np.asarray(out)[0]) == 3.0:
                    return hvd.rank()

        r = train(state)
        print(f"rank {{r}} done in world of {{hvd.size()}}", flush=True)
        hvd.shutdown()
    """))

    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
    driver = ElasticDriver(
        HostDiscoveryScript(str(disco)), [sys.executable, str(prog)],
        min_np=2, max_np=3, reset_limit=3, ckpt_dir=str(tmp_path))
    rc = driver.run()
    assert rc == 0
    boots = boot_log.read_text().strip().splitlines()
    # exactly three process boots: ranks 0,1 once each (NOT restarted on
    # growth) + the new rank 2
    assert len(boots) == 3, boots
    booted_ranks = sorted(line.split()[1] for line in boots)
    assert booted_ranks == ["rank=0", "rank=1", "rank=2"]


def _inplace_worker_prog(log, tmp_path, crash_clause):
    """Shared worker for the in-place recovery tests: loop of allreduce +
    commit until step 8, logging BOOT/DONE with the process PID."""
    return textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu import elastic

        orig_rank = int(os.environ["HOROVOD_RANK"])
        hvd.init()
        with open({str(log)!r}, "a") as f:
            f.write(f"BOOT rank={{orig_rank}} pid={{os.getpid()}}\\n")

        state = elastic.ObjectState(name="inplace", step=0)

        @elastic.run
        def train(state):
            while True:
{crash_clause}
                out = hvd.allreduce(
                    np.ones(2, np.float32), op=hvd.Sum,
                    name=f"s{{hvd.size()}}.{{state.step}}")
                state.step += 1
                time.sleep(0.4)  # give the driver's 1s discovery a shot
                state.commit()
                if state.step >= 8:
                    return float(np.asarray(out)[0])

        out = train(state)
        assert out == float(hvd.size()), (out, hvd.size())
        # re-mesh timeline evidence (docs/OBSERVABILITY.md "Re-mesh
        # timeline"): a worker that lived through an in-place re-mesh
        # carries hvd_remesh_seconds{{phase}} observations + the episode
        # counter; a freshly booted replacement carries none
        import re as _re
        from horovod_tpu.metrics.registry import default_registry
        snap = default_registry().snapshot()
        phases = sorted({{
            _re.search(r'phase="([^"]+)"', k).group(1)
            for k, s in snap.items()
            if k.startswith('hvd_remesh_seconds{{') and s["count"] > 0}})
        total = snap.get("hvd_remesh_total", {{}}).get("value", 0)
        with open({str(log)!r}, "a") as f:
            f.write(f"DONE rank={{hvd.rank()}} pid={{os.getpid()}} "
                    f"size={{hvd.size()}} step={{state.step}}\\n")
            f.write(f"REMESH rank={{hvd.rank()}} total={{int(total)}} "
                    f"phases={{','.join(phases)}}\\n")
        hvd.shutdown()
    """)


def test_elastic_crash_recovers_in_place_with_replacement(tmp_path):
    """A worker CRASHES mid-training: survivors catch
    HorovodInternalError, receive the driver's recovery world doc, and
    re-rendezvous IN PLACE — no process restart (PIDs unchanged), params
    stay in host memory — while the driver respawns a REPLACEMENT for
    the lost rank on the free slot (VERDICT r4 missing #5; reference:
    the reset loop, common/elastic.py:151-175)."""
    log = tmp_path / "events.log"
    marker = tmp_path / "crashed_once"
    crash = (f"                if orig_rank == 2 and state.step >= 3 "
             f"and not os.path.exists({str(marker)!r}):\n"
             f"                    open({str(marker)!r}, 'w').close()\n"
             f"                    os._exit(1)\n")
    prog = tmp_path / "train.py"
    prog.write_text(_inplace_worker_prog(log, tmp_path, crash))

    from horovod_tpu.runner.elastic.driver import ElasticDriver
    driver = ElasticDriver(
        FixedHosts([HostInfo("localhost", 3)]),
        [sys.executable, str(prog)],
        min_np=2, max_np=3, reset_limit=3, ckpt_dir=str(tmp_path))
    rc = driver.run()
    assert rc == 0
    lines = log.read_text().strip().splitlines()
    boots = [l for l in lines if l.startswith("BOOT")]
    dones = [l for l in lines if l.startswith("DONE")]
    # 4 boots: the original 3 + ONE replacement; survivors not restarted
    assert len(boots) == 4, lines
    assert len(dones) == 3, lines
    boot_pids = {}
    for b in boots:
        parts = dict(p.split("=") for p in b.split()[1:])
        boot_pids.setdefault(parts["rank"], []).append(parts["pid"])
    assert len(boot_pids["0"]) == 1 and len(boot_pids["1"]) == 1
    assert len(boot_pids["2"]) == 2  # crasher + its replacement
    for d in dones:
        parts = dict(p.split("=") for p in d.split()[1:])
        assert parts["size"] == "3"  # world healed back to full size
        # survivors finish under the PID they booted with
        if parts["rank"] in ("0", "1"):
            assert boot_pids[parts["rank"]] == [parts["pid"]]
    # the re-mesh phase timeline (ISSUE 9): every survivor measured its
    # recovery — hvd_remesh_seconds{phase} series exist for the full
    # pipeline and the episode counter ticked — while the freshly
    # booted replacement measured none (it never re-meshed)
    remesh = {}
    for l in lines:
        if l.startswith("REMESH"):
            parts = dict(p.split("=") for p in l.split()[1:])
            remesh[parts["rank"]] = parts
    assert set(remesh) == {"0", "1", "2"}, lines
    full_pipeline = {"failure_detect", "drain", "rendezvous", "rebuild",
                     "restore", "first_step"}
    for r in ("0", "1"):  # the survivors
        assert int(remesh[r]["total"]) >= 1, remesh[r]
        phases = set(remesh[r]["phases"].split(","))
        assert phases >= full_pipeline, (r, phases)
    assert int(remesh["2"]["total"]) == 0, remesh["2"]


def test_elastic_capacity_loss_shrinks_in_place(tmp_path):
    """Discovery DROPS a slot mid-training (planned downscale): the kept
    workers resync into the smaller world at their next commit IN PLACE
    (PIDs unchanged, no generation restart); the dropped worker exits
    via the not-in-new-world path."""
    log = tmp_path / "events.log"
    disco = tmp_path / "discover.sh"
    disco.write_text(
        "#!/bin/bash\n"
        f"if [ -f {tmp_path}/shrink ]; then echo localhost:2; "
        "else echo localhost:3; fi\n")
    disco.chmod(disco.stat().st_mode | stat.S_IEXEC)
    shrink_marker = (f"                if orig_rank == 0 and "
                     f"state.step == 2:\n"
                     f"                    open(os.path.join("
                     f"{str(tmp_path)!r}, 'shrink'), 'w').close()\n")
    prog = tmp_path / "train.py"
    prog.write_text(_inplace_worker_prog(log, tmp_path, shrink_marker))

    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
    driver = ElasticDriver(
        HostDiscoveryScript(str(disco)), [sys.executable, str(prog)],
        min_np=2, max_np=3, reset_limit=3, ckpt_dir=str(tmp_path))
    rc = driver.run()
    assert rc == 0
    lines = log.read_text().strip().splitlines()
    boots = {l.split()[1]: l.split()[2] for l in lines
             if l.startswith("BOOT")}
    dones = [l for l in lines if l.startswith("DONE")]
    # exactly 3 boots (nobody restarted) and 2 finishers in the 2-world
    assert len([l for l in lines if l.startswith("BOOT")]) == 3, lines
    assert sorted(boots) == ["rank=0", "rank=1", "rank=2"]
    assert len(dones) == 2, lines
    for d in dones:
        parts = dict(p.split("=") for p in d.split()[1:])
        assert parts["size"] == "2"
        # the finishing PID is the booting PID: in-place shrink
        assert boots[f"rank={parts['rank']}"] == f"pid={parts['pid']}"


def _growth_agent_main(ordinal, kv_port, secret_hex, world_secret_hex):
    """multiprocessing target for the growth test: module-level with
    scalar args so it pickles under any mp start method (agent.py's ctx
    must never be captured by framework closures)."""
    from horovod_tpu.runner.elastic.agent import agent_loop
    agent_loop(ordinal, "127.0.0.1", kv_port, secret_hex,
               world_secret_hex)


@needs_core
def test_agent_elastic_growth_resync_collects_results(tmp_path):
    """Agent-transport elastic (the Spark/Ray substrate) with IN-PLACE
    growth: the second host agent appears only after generation 0 has
    launched at np=1, the driver grows the running generation, the
    surviving rank resyncs at commit (its HVD_ELASTIC_GENERATION moves
    forward), and run_agent_elastic still collects its result — the
    growth-resync scenario of the r4 review."""
    import multiprocessing
    import threading

    from horovod_tpu.runner.elastic.agent import run_agent_elastic

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def train():
        import time

        import numpy as np

        import horovod_tpu as hvd
        import horovod_tpu.elastic as elastic

        hvd.init()
        state = elastic.ObjectState(name="agent_growth", tick=0)

        @elastic.run
        def wait_for_two(state):
            # a long "step" that only ends once growth has landed; the
            # commit both snapshots and polls the world doc
            deadline = time.time() + 60
            while hvd.size() < 2:
                if time.time() > deadline:
                    raise RuntimeError("growth never arrived")
                time.sleep(0.3)
                state.tick += 1
                state.commit()

        wait_for_two(state)
        out = hvd.allreduce(np.ones(1, np.float32), op=hvd.Sum, name="gr")
        val = float(np.asarray(out)[0])
        hvd.shutdown()
        return val

    def start_agents(ctx):
        procs = []
        args = (ctx["kv_port"], ctx["secret_hex"],
                ctx["world_secret_hex"])
        kv = ctx["kv"]

        def launch(ordinal):
            p = multiprocessing.Process(
                target=_growth_agent_main, args=(ordinal,) + args,
                daemon=True)
            p.start()
            procs.append(p)

        launch(0)

        def late_joiner():
            # deterministic growth: the second "host" appears only once
            # generation 0 has provably launched (its worker command doc
            # reached agent 0 through the KV)
            deadline = time.time() + 60
            while not kv.scope("cmd") and time.time() < deadline:
                time.sleep(0.1)
            launch(1)

        joiner = threading.Thread(target=late_joiner, daemon=True)
        joiner.start()

        def cleanup():
            joiner.join(timeout=70)
            for p in procs:
                p.join(timeout=15)
            for p in procs:
                if p.is_alive():
                    p.terminate()

        return cleanup

    results = run_agent_elastic(
        start_agents, train, num_proc=2, min_np=1, max_np=2,
        env={"PYTHONPATH": repo, "JAX_PLATFORMS": "cpu"})
    # the essential (launch-generation) world was np=1: one result,
    # computed AFTER growth at world size 2
    assert results == [2.0], results
