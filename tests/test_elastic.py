"""Elastic tests: driver logic with mocked discovery (reference analog:
test/single/test_elastic_driver.py — simulated host add/remove, rank
stability, blacklisting) and a real fake-cluster integration run on
localhost (reference analog: test/integration/elastic_common.py:34-118 —
a discovery script whose output changes over time + scripted failures)."""

import os
import stat
import subprocess
import sys
import textwrap
import time

import pytest

from horovod_tpu.core import core_available
from horovod_tpu.runner.elastic.discovery import (FixedHosts, HostDiscovery,
                                                  HostManager)
from horovod_tpu.runner.elastic.registration import (FAILURE, SUCCESS,
                                                     WorkerStateRegistry)
from horovod_tpu.runner.hosts import HostInfo

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class MockDiscovery(HostDiscovery):
    """Scripted sequence of host sets (reference analog: the elastic tests'
    fake discovery)."""

    def __init__(self, sequences):
        self._sequences = sequences
        self._idx = 0

    def find_available_hosts_and_slots(self):
        seq = self._sequences[min(self._idx, len(self._sequences) - 1)]
        self._idx += 1
        return dict(seq)


def test_host_manager_change_detection():
    disc = MockDiscovery([{"a": 2}, {"a": 2}, {"a": 2, "b": 2}, {"b": 2}])
    hm = HostManager(disc)
    assert hm.update_available_hosts() is True       # initial
    assert hm.update_available_hosts() is False      # no change
    assert hm.update_available_hosts() is True       # b added
    # rank stability: 'a' keeps its position while it exists
    assert [h.hostname for h in hm.current_hosts()] == ["a", "b"]
    assert hm.update_available_hosts() is True       # a removed
    assert [h.hostname for h in hm.current_hosts()] == ["b"]


def test_host_manager_blacklist():
    disc = MockDiscovery([{"a": 2, "b": 2}])
    hm = HostManager(disc)
    hm.blacklist("b")
    hm.update_available_hosts()
    assert [h.hostname for h in hm.current_hosts()] == ["a"]
    assert hm.slot_count() == 2


def test_worker_state_registry():
    reg = WorkerStateRegistry(reset_limit=2)
    reg.reset(2)
    reg.record(0, "a", SUCCESS)
    reg.record(1, "b", FAILURE)
    assert reg.count(SUCCESS) == 1
    assert reg.count(FAILURE) == 1
    assert reg.failed_hosts() == {"b": 1}
    assert not reg.reset_limit_reached()
    reg.reset(2)
    reg.reset(2)
    assert reg.reset_limit_reached()


def test_object_state_commit_restore(hvd, tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    from horovod_tpu.elastic import ObjectState
    st = ObjectState(name="t1", epoch=0, w=[1.0, 2.0])
    st.epoch = 5
    st.w = [9.0, 9.0]
    st.commit()
    st.epoch = 7
    st.restore()
    assert st.epoch == 5 and st.w == [9.0, 9.0]
    # a fresh process (new State object) resumes from the committed file
    st2 = ObjectState(name="t1", epoch=0, w=[0.0])
    assert st2.epoch == 5 and st2.w == [9.0, 9.0]


def test_elastic_run_decorator_retries(hvd, tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    from horovod_tpu import elastic

    state = elastic.ObjectState(name="t2", count=0)
    attempts = []

    @elastic.run
    def train(state):
        attempts.append(1)
        if len(attempts) < 3:
            raise elastic.HorovodInternalError("simulated collective fail")
        return state.count

    assert train(state) == 0
    assert len(attempts) == 3


needs_core = pytest.mark.skipif(not core_available(),
                                reason="libhvdcore.so not built")


@needs_core
def test_elastic_integration_fake_cluster(tmp_path):
    """Real elastic run on localhost: the discovery script's output changes
    with an epoch file, worker of generation 0 fails once, generation 1
    succeeds resuming from committed state (reference analog:
    test/integration/elastic_common.py scripted discovery + exit)."""
    epoch_file = tmp_path / "epoch"
    epoch_file.write_text("0")
    disco = tmp_path / "discover.sh"
    disco.write_text("#!/bin/bash\necho localhost:2\n")
    disco.chmod(disco.stat().st_mode | stat.S_IEXEC)

    prog = tmp_path / "train.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys
        sys.path.insert(0, {REPO!r})
        import numpy as np
        from horovod_tpu.core.core_backend import CoreBackend
        from horovod_tpu.ops.reduce_op import ReduceOp
        from horovod_tpu import elastic

        be = CoreBackend()
        state = elastic.ObjectState(name="itg", step=0)
        gen = int(os.environ.get("HVD_ELASTIC_GENERATION", 0))
        # first generation: rank 1 crashes at step 2 after committing step 1
        for step in range(state.step, 5):
            out = be.allreduce_async(f"s{{step}}",
                                     np.ones(4, np.float32),
                                     ReduceOp.SUM).wait(30)
            state.step = step + 1
            state.save()
            if gen == 0 and be.rank == 1 and step == 1:
                os._exit(17)
        print(f"rank {{be.rank}} gen {{gen}} finished at step "
              f"{{state.step}}", flush=True)
        assert state.step == 5
        be.shutdown()
    """))

    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
    driver = ElasticDriver(
        HostDiscoveryScript(str(disco)), [sys.executable, str(prog)],
        min_np=2, max_np=2, reset_limit=3, ckpt_dir=str(tmp_path))
    rc = driver.run()
    assert rc == 0


@pytest.mark.skipif(not core_available(),
                    reason="libhvdcore.so not built")
def test_elastic_growth_does_not_restart_survivors(tmp_path):
    """Scale-up extends the running generation (VERDICT r1 #6): the
    discovery output grows 2 -> 3 slots mid-run; survivors pick the new
    world up at commit() via HostsUpdatedInterrupt and re-init IN PLACE
    (each rank boots exactly once), the new worker joins, and a
    3-rank collective completes."""
    boot_log = tmp_path / "boots.log"
    disco = tmp_path / "discover.sh"
    # discovery reports 2 slots until the grow-marker appears
    disco.write_text(
        "#!/bin/bash\n"
        f"if [ -f {tmp_path}/grow ]; then echo localhost:3; "
        "else echo localhost:2; fi\n")
    disco.chmod(disco.stat().st_mode | stat.S_IEXEC)

    prog = tmp_path / "train.py"
    prog.write_text(textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu import elastic

        hvd.init()
        with open({str(boot_log)!r}, "a") as f:
            f.write(f"BOOT rank={{hvd.rank()}} pid={{os.getpid()}}\\n")
        if hvd.rank() == 0 and hvd.size() == 2:
            open(os.path.join({str(tmp_path)!r}, "grow"), "w").close()

        state = elastic.ObjectState(name="grow", step=0)

        @elastic.run
        def train(state):
            while True:
                out = hvd.allreduce(np.ones(2, np.float32), op=hvd.Sum,
                                    name=f"g{{hvd.size()}}.{{state.step}}")
                state.step += 1
                time.sleep(0.3)
                state.commit()   # raises HostsUpdatedInterrupt on growth
                if hvd.size() >= 3 and float(np.asarray(out)[0]) == 3.0:
                    return hvd.rank()

        r = train(state)
        print(f"rank {{r}} done in world of {{hvd.size()}}", flush=True)
        hvd.shutdown()
    """))

    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.elastic.discovery import HostDiscoveryScript
    driver = ElasticDriver(
        HostDiscoveryScript(str(disco)), [sys.executable, str(prog)],
        min_np=2, max_np=3, reset_limit=3, ckpt_dir=str(tmp_path))
    rc = driver.run()
    assert rc == 0
    boots = boot_log.read_text().strip().splitlines()
    # exactly three process boots: ranks 0,1 once each (NOT restarted on
    # growth) + the new rank 2
    assert len(boots) == 3, boots
    booted_ranks = sorted(line.split()[1] for line in boots)
    assert booted_ranks == ["rank=0", "rank=1", "rank=2"]
