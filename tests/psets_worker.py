"""Worker stressing concurrent disjoint process sets (reference analog:
test/parallel/test_process_sets_*): sets {0,1} and {2,3} run independent
collectives at the same time over their own coordination domains."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np  # noqa: E402

from horovod_tpu.core.core_backend import CoreBackend  # noqa: E402
from horovod_tpu.ops.reduce_op import ReduceOp  # noqa: E402


def main():
    be = CoreBackend()
    rank, size = be.rank, be.size
    assert size == 4
    # all ranks register both sets in the same order (ids stay aligned)
    low = be.make_subset([0, 1])
    high = be.make_subset([2, 3])
    mine = low if rank < 2 else high
    peer_base = 0 if rank < 2 else 2

    # each set allreduces its own tensors concurrently with the other set
    for it in range(10):
        x = np.full((64,), float(rank + 1), np.float32)
        out = mine.allreduce_async(f"ps.{it}", x, ReduceOp.SUM).wait(60)
        expect = (peer_base + 1.0) + (peer_base + 2.0)
        np.testing.assert_allclose(out, expect)
        # interleave a global-set op to stress cross-domain cycles
        g = be.allreduce_async(f"glob.{it}", np.ones(8, np.float32),
                               ReduceOp.SUM).wait(60)
        np.testing.assert_allclose(g, 4.0)

    # ragged allgather within the subset
    rows = mine.rank + 1
    out = mine.allgather_async(
        "ps.ag", np.full((rows, 2), float(rank), np.float32)).wait(60)
    assert out.shape[0] == 3  # 1 + 2 rows
    be.barrier()
    be.shutdown()
    print(f"psets worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
