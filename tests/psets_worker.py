"""Worker stressing concurrent disjoint process sets (reference analog:
test/parallel/test_process_sets_*): sets {0,1} and {2,3} run independent
collectives at the same time over their own coordination domains.

Backend-agnostic: uses the public API so the same script validates the TCP
core (default) and the XLA data plane (HOROVOD_TPU_OPERATIONS=XLA_EAGER).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    hvd.init()
    assert hvd.rank() == rank and hvd.size() == size == 4
    # Regression knob for the registration race (r2): one rank registers
    # seconds after the others; inactive-until-consensus must absorb the
    # skew instead of deadlocking the domain-0 lockstep.
    if rank == int(os.environ.get("HVD_TEST_REG_DELAY_RANK", "-1")):
        import time
        time.sleep(float(os.environ.get("HVD_TEST_REG_DELAY_SECS", "2")))
    # all ranks register both sets in the same order (ids stay aligned)
    low = hvd.add_process_set([0, 1])
    high = hvd.add_process_set([2, 3])
    mine = low if rank < 2 else high
    peer_base = 0 if rank < 2 else 2

    # each set allreduces its own tensors concurrently with the other set
    for it in range(10):
        x = np.full((64,), float(rank + 1), np.float32)
        out = hvd.allreduce(x, op=hvd.Sum, name=f"ps.{it}",
                            process_set=mine)
        expect = (peer_base + 1.0) + (peer_base + 2.0)
        np.testing.assert_allclose(np.asarray(out), expect)
        # interleave a global-set op to stress cross-domain cycles
        g = hvd.allreduce(np.ones(8, np.float32), op=hvd.Sum,
                          name=f"glob.{it}")
        np.testing.assert_allclose(np.asarray(g), 4.0)

    # grouped (fused) allreduce within the subset
    outs = hvd.grouped_allreduce(
        [np.full(5, float(rank), np.float32),
         np.full((2, 3), 1.0, np.float32)],
        op=hvd.Sum, name="ps.grp", process_set=mine)
    np.testing.assert_allclose(np.asarray(outs[0]),
                               float(peer_base) + peer_base + 1.0)
    np.testing.assert_allclose(np.asarray(outs[1]), 2.0)

    # ragged allgather within the subset
    set_rank = mine.rank()
    rows = set_rank + 1
    out = hvd.allgather(np.full((rows, 2), float(rank), np.float32),
                        name="ps.ag", process_set=mine)
    assert np.asarray(out).shape[0] == 3  # 1 + 2 rows

    # broadcast with a GLOBAL root rank (reference semantics)
    root = peer_base + 1
    b = hvd.broadcast(np.full(3, float(rank), np.float32),
                      root_rank=root, name="ps.bc", process_set=mine)
    np.testing.assert_allclose(np.asarray(b), float(root))

    # per-set join must reject on the same-order XLA data plane (the
    # subset backend shares the global backend's no-negotiation limit)
    if os.environ.get("HOROVOD_TPU_OPERATIONS", "").upper() == "XLA_EAGER":
        from horovod_tpu.ops.collectives import _backend_for
        try:
            _backend_for(mine).join()
            raise AssertionError("subset join must raise on XLA eager")
        except NotImplementedError:
            pass

    hvd.barrier()
    hvd.shutdown()
    print(f"psets worker {rank}: OK", flush=True)


if __name__ == "__main__":
    main()
