"""Tests for sync-BN, BERT, data loaders, callbacks."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
import optax
from functools import partial
from jax.sharding import PartitionSpec as P

from horovod_tpu._compat import shard_map
from horovod_tpu.parallel import build_mesh


# -- sync batch norm ---------------------------------------------------------

def test_sync_batch_norm_spmd_matches_global():
    from horovod_tpu.train.sync_batch_norm import sync_batch_norm_spmd
    mesh = build_mesh(dp=8)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(16, 4), jnp.float32)  # batch sharded over dp
    scale = jnp.ones(4)
    bias = jnp.zeros(4)

    @partial(shard_map, mesh=mesh, in_specs=(P("dp"), P(), P()),
             out_specs=P("dp"))
    def synced(xl, s, b):
        return sync_batch_norm_spmd(xl, s, b, axis_names=("dp",))

    out = synced(x, scale, bias)
    # oracle: normalize with GLOBAL batch moments
    xf = np.asarray(x)
    mean, var = xf.mean(0), xf.var(0)
    expect = (xf - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-4, atol=1e-4)


def test_sync_batch_norm_module():
    from horovod_tpu.train.sync_batch_norm import SyncBatchNorm
    m = SyncBatchNorm(axis_names=())
    x = jnp.asarray(np.random.RandomState(1).randn(8, 4), jnp.float32)
    variables = m.init(jax.random.PRNGKey(0), x)
    y, mut = m.apply(variables, x, mutable=["batch_stats"])
    assert y.shape == x.shape
    np.testing.assert_allclose(np.asarray(y).mean(0), 0.0, atol=1e-4)
    # eval path with running stats
    y2 = m.apply({"params": variables["params"],
                  "batch_stats": mut["batch_stats"]}, x,
                 use_running_average=True)
    assert np.all(np.isfinite(np.asarray(y2)))


def test_sync_batch_norm_running_var_unbiased():
    """Running var must carry the unbiased n/(n-1) estimate (reference torch
    SyncBatchNorm applies the global-count correction; ADVICE r1)."""
    from horovod_tpu.train.sync_batch_norm import SyncBatchNorm
    m = SyncBatchNorm(axis_names=(), momentum=0.0)  # ra_var = this batch's
    x = jnp.asarray(np.random.RandomState(2).randn(16, 4), jnp.float32)
    variables = m.init(jax.random.PRNGKey(0), x)
    _, mut = m.apply(variables, x, mutable=["batch_stats"])
    n = x.shape[0]
    expect = np.asarray(x).var(0) * n / (n - 1)  # unbiased
    np.testing.assert_allclose(np.asarray(mut["batch_stats"]["var"]),
                               expect, rtol=1e-5)


# -- BERT --------------------------------------------------------------------

def _tiny_bert():
    from horovod_tpu.models.bert import Bert, BertConfig
    cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                     num_heads=4, intermediate_size=64, max_position=32,
                     dtype=jnp.float32)
    return Bert(cfg), cfg


def _bert_batch(B=8, S=16, vocab=64, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "input_ids": jnp.asarray(rng.randint(0, vocab, (B, S)), jnp.int32),
        "token_type_ids": jnp.zeros((B, S), jnp.int32),
        "attention_mask": jnp.ones((B, S), bool),
        "mlm_labels": jnp.asarray(rng.randint(0, vocab, (B, S)), jnp.int32),
        "mlm_mask": jnp.asarray(rng.rand(B, S) < 0.15, jnp.float32),
        "nsp_labels": jnp.asarray(rng.randint(0, 2, (B,)), jnp.int32),
    }


def test_bert_train_step_dp_tp():
    from horovod_tpu.models.bert import init_bert, make_bert_train_step
    mesh = build_mesh(dp=4, tp=2)
    model, cfg = _tiny_bert()
    params = init_bert(model, jax.random.PRNGKey(0), seq_len=16, mesh=mesh)
    tx = optax.adamw(1e-3)
    opt_state = jax.jit(tx.init)(params)
    step = make_bert_train_step(model, tx, mesh)
    batch = _bert_batch()
    losses = []
    for i in range(4):
        params, opt_state, loss = step(params, opt_state, batch)
        jax.block_until_ready(loss)
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_bert_tp_sharding_applied():
    from horovod_tpu.models.bert import init_bert
    import flax.linen as nn
    mesh = build_mesh(dp=4, tp=2)
    model, cfg = _tiny_bert()
    params = init_bert(model, jax.random.PRNGKey(0), seq_len=16, mesh=mesh)
    qkern = params["layer_0"]["attention"]["query"]["kernel"]
    assert isinstance(qkern, nn.Partitioned)
    shard_shape = qkern.value.sharding.shard_shape(qkern.value.shape)
    assert shard_shape[1] == qkern.value.shape[1] // 2  # heads split by tp


# -- data loaders ------------------------------------------------------------

def test_sharded_dataset_partition():
    from horovod_tpu.data import ShardedDataset
    data = list(range(103))
    seen = []
    for r in range(4):
        ds = ShardedDataset(data, rank=r, size=4, shuffle=True, seed=7)
        items = list(ds)
        assert len(items) == len(ds) == 103 // 4
        seen.extend(items)
    assert len(seen) == len(set(seen))  # disjoint
    # deterministic given epoch
    ds = ShardedDataset(data, rank=1, size=4, shuffle=True, seed=7)
    a = list(ds)
    ds.set_epoch(0)
    assert list(ds) == a
    ds.set_epoch(1)
    assert list(ds) != a


def test_async_loader_prefetch():
    from horovod_tpu.data import AsyncDataLoaderMixin, BaseDataLoader

    class Loader(BaseDataLoader):
        def __len__(self):
            return 10

        def _iterate(self):
            yield from range(10)

    class AsyncLoader(AsyncDataLoaderMixin, Loader):
        pass

    loader = AsyncLoader(async_loader_queue_size=4)
    assert list(loader) == list(range(10))
    assert list(loader) == list(range(10))  # reusable
    loader.close_async_loader()

    sync_loader = AsyncLoader(async_loader_queue_size=0)
    assert list(sync_loader) == list(range(10))


def test_device_prefetch_pipeline():
    """device_prefetch keeps batches on device ahead of the consumer:
    values arrive in order, already device-resident, honoring a mesh
    sharding, and the buffer never holds more than buffer_size items."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.data import ShardedDataset, device_prefetch

    batches = [np.full((8, 4), i, np.float32) for i in range(6)]
    got = list(device_prefetch(iter(batches)))
    assert len(got) == 6
    for i, b in enumerate(got):
        assert isinstance(b, jax.Array)  # already on device
        np.testing.assert_array_equal(np.asarray(b), batches[i])

    mesh = hvd.build_mesh(dp=-1)
    sh = NamedSharding(mesh, P("dp"))
    got = list(device_prefetch(iter(batches), sharding=sh))
    assert got[0].sharding == sh  # placed per the requested sharding

    # composes with ShardedDataset + pytree batches
    ds = ShardedDataset([{"x": np.ones(2) * i} for i in range(8)],
                        rank=0, size=2, shuffle=False)
    out = list(device_prefetch(ds, buffer_size=3))
    assert [float(np.asarray(b["x"])[0]) for b in out] == [0.0, 2.0, 4.0,
                                                           6.0]

    # boundedness: never pulls more than buffer_size ahead of the consumer
    pulled = []

    def counting():
        for i in range(10):
            pulled.append(i)
            yield np.float32(i)

    gen = device_prefetch(counting(), buffer_size=2)
    for n_consumed, _ in enumerate(gen, start=1):
        assert len(pulled) <= n_consumed + 2, (len(pulled), n_consumed)
    assert len(pulled) == 10

    # misconfiguration fails AT THE CALL, not at first iteration
    with pytest.raises(ValueError, match="buffer_size"):
        device_prefetch(iter(batches), buffer_size=0)

    # mid-stream source error: already-transferred batches drain first,
    # then the error surfaces at its true stream position
    def flaky():
        for i in range(5):
            if i == 3:
                raise RuntimeError("decode failed")
            yield np.float32(i)

    gen = device_prefetch(flaky(), buffer_size=2)
    seen = []
    with pytest.raises(RuntimeError, match="decode failed"):
        for b in gen:
            seen.append(float(np.asarray(b)))
    assert seen == [0.0, 1.0, 2.0]


# -- callbacks ---------------------------------------------------------------

def test_metric_average_callback_single(hvd):
    from horovod_tpu.train.callbacks import MetricAverageCallback
    cb = MetricAverageCallback()
    out = cb.on_epoch_end({"loss": 1.5, "name": "x"})
    assert out == {"loss": 1.5, "name": "x"}


def test_broadcast_callback_single(hvd):
    from horovod_tpu.train.callbacks import BroadcastGlobalVariablesCallback
    cb = BroadcastGlobalVariablesCallback(0)
    p = {"w": jnp.ones(3)}
    out = cb.on_train_begin(p)
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)


def test_lr_warmup_schedule(hvd):
    from horovod_tpu.train.callbacks import LearningRateWarmupCallback
    cb = LearningRateWarmupCallback(0.1, warmup_epochs=2, steps_per_epoch=10)
    sched = cb.schedule()
    # size 1: flat schedule
    np.testing.assert_allclose(float(sched(0)), 0.1)
    np.testing.assert_allclose(float(sched(100)), 0.1)
