"""Worker: ResponseCache LRU eviction under pressure, fused-allgather
displacement math vs a per-tensor oracle, and dynamic timeline restart
(ADVICE r3: the subtlest cross-rank-determinism logic had no test).

Launched by test_core_multiprocess.py with HOROVOD_CACHE_CAPACITY small
enough that the name working set cannot fit, so evictions + pending-bit
migration happen mid-run (reference analog: response_cache tests around
``horovod/common/response_cache.cc``)."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from horovod_tpu.core.core_backend import CoreBackend  # noqa: E402
from horovod_tpu.ops.reduce_op import ReduceOp  # noqa: E402


def main():
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    be = CoreBackend()

    # -- LRU eviction pressure ------------------------------------------------
    # capacity (4, set by the test) << 12 distinct names, cycled for six
    # epochs: each epoch re-inserts evicted names while a wavefront of
    # still-pending requests holds cache bits in flight — the eviction +
    # bit-migration path must keep bit spaces rank-aligned or results
    # diverge/deadlock. Submit the whole wavefront async before waiting so
    # cached and uncached requests share negotiation cycles.
    names = [f"cache.{i}" for i in range(12)]
    for epoch in range(6):
        handles = []
        for i, name in enumerate(names):
            x = np.full((32,), float(rank + i + epoch), np.float32)
            handles.append((i, be.allreduce_async(name, x, ReduceOp.SUM)))
        for i, h in handles:
            out = h.wait()
            expect = float(sum(r + i + epoch for r in range(size)))
            np.testing.assert_allclose(out, np.full((32,), expect),
                                       rtol=1e-6)
    c = be.counters()
    assert c["cache_evictions"] > 0, c

    # deterministic hit phase: one hot name submitted sequentially stays
    # resident between submissions (no competing inserts), so every repeat
    # after the first MUST hit regardless of how the negotiation batches
    # the epochs above
    for j in range(5):
        out = be.allreduce_async("cache.hot",
                                 np.full((16,), float(rank + j), np.float32),
                                 ReduceOp.SUM).wait()
        np.testing.assert_allclose(
            out, np.full((16,), float(sum(r + j for r in range(size)))),
            rtol=1e-6)
    c = be.counters()
    assert c["cache_hits"] > 0, c

    # -- fused allgather vs per-tensor oracle ---------------------------------
    # ten small ragged allgathers submitted concurrently fuse into shared
    # units (the test also sets a tiny fusion threshold to force unit
    # splits); every tensor's displacement math must reproduce exactly what
    # a lone allgather would return.
    def shard(r, i):
        rows = (r + i) % 3 + 1
        return (np.arange(rows * (i + 1), dtype=np.float32)
                .reshape(rows, i + 1) + 1000 * r + i)

    handles = [(i, be.allgather_async(f"fag.{i}", shard(rank, i)))
               for i in range(10)]
    for i, h in handles:
        out = h.wait()
        expect = np.concatenate([shard(r, i) for r in range(size)])
        np.testing.assert_allclose(out, expect)
    assert be.counters()["bytes_allgathered"] > 0

    # -- dynamic timeline restart ---------------------------------------------
    # stop + start at a new path while collectives keep flowing; both files
    # must parse (test side asserts) and the engine must stay correct.
    tl1, tl2 = os.environ.get("HVD_TEST_TL1"), os.environ.get("HVD_TEST_TL2")
    if tl1 and tl2:
        be.start_core_timeline(tl1, mark_cycles=True)
        out = be.allreduce_async("tl.a", np.ones(8, np.float32),
                                 ReduceOp.SUM).wait()
        np.testing.assert_allclose(out, np.full(8, float(size)))
        be.stop_core_timeline()
        be.start_core_timeline(tl2)
        out = be.allreduce_async("tl.b", np.ones(8, np.float32),
                                 ReduceOp.SUM).wait()
        np.testing.assert_allclose(out, np.full(8, float(size)))
        be.stop_core_timeline()

    be.barrier()
    be.shutdown()
    print(f"worker {rank}: OK")


if __name__ == "__main__":
    main()
