"""Unit tests for the online anomaly engine (docs/OBSERVABILITY.md
"Anomaly engine"): EWMA+MAD baseline behavior, the four detector kinds,
hysteresis (one finding per episode), baseline freezing under anomaly,
the zero-false-positive bar on clean/noisy series, and — the ISSUE 7
acceptance — an injected slow-step window (PR-5 chaos ``step`` stall
seam) flagged as ``step_time_drift`` with a flight event and an autopsy
summary naming the degradation, while an identical clean run flags
nothing."""

import json
import os
import random

import pytest

from horovod_tpu.metrics.anomaly import AnomalyEngine, EwmaMad
from horovod_tpu.metrics.registry import Registry


@pytest.fixture(autouse=True)
def _fresh_singletons(monkeypatch):
    import horovod_tpu.profiling as profiling
    from horovod_tpu.metrics import anomaly, timeseries
    # this file tests the ENGINE; the unit findings it manufactures
    # must not arm real device-trace captures (the capture path has its
    # own battery + acceptance in test_profiling.py) — an armed capture
    # would open during the next telemetry loop and skew its baseline
    monkeypatch.setenv("HVD_TPU_PROFILE_ON_ANOMALY", "0")
    anomaly.reset()
    timeseries.reset()
    profiling.reset()
    yield
    anomaly.reset()
    timeseries.reset()
    profiling.reset()


def _engine():
    return AnomalyEngine(registry=Registry())


def _counter(eng, kind):
    c = eng._reg.get("hvd_anomaly_total", labels={"kind": kind})
    return c.value if c is not None else 0.0


# -- baseline ---------------------------------------------------------------

def test_ewma_mad_tracks_and_floors():
    b = EwmaMad(alpha=0.2)
    for _ in range(50):
        b.update(1.0)
    assert b.mean == pytest.approx(1.0)
    # deviation floored relative to the mean: a perfectly flat series
    # must not become infinitely sensitive
    assert b.deviation() >= 0.05 * 1.0
    for _ in range(200):
        b.update(2.0)
    assert b.mean == pytest.approx(2.0, rel=0.01)


# -- step-time drift --------------------------------------------------------

def test_clean_run_flags_nothing():
    eng = _engine()
    rng = random.Random(7)
    for i in range(500):  # jittery but healthy: +-20% around 10ms
        dt = 0.010 * (1.0 + 0.2 * (rng.random() - 0.5))
        assert eng.observe_step(i, dt, units_per_s=32 / dt) == []
    assert eng.recent_findings() == []


def test_step_time_drift_flagged_once_per_episode():
    eng = _engine()
    for i in range(30):
        eng.observe_step(i, 0.010)
    findings = []
    for i in range(30, 40):  # 10 stalled steps, one episode
        findings += eng.observe_step(i, 0.200)
    assert len(findings) == 1, findings
    f = findings[0]
    assert f["kind"] == "step_time_drift"
    assert f["value"] == pytest.approx(0.2)
    assert f["baseline"] == pytest.approx(0.010, rel=0.05)
    assert _counter(eng, "step_time_drift") == 1
    # recovery, then a second degradation: a NEW episode flags again
    for i in range(40, 60):
        assert eng.observe_step(i, 0.010) == []
    findings = []
    for i in range(60, 70):
        findings += eng.observe_step(i, 0.200)
    assert len(findings) == 1
    assert _counter(eng, "step_time_drift") == 2


def test_baseline_refuses_to_learn_from_the_stall():
    eng = _engine()
    for i in range(20):
        eng.observe_step(i, 0.010)
    for i in range(20, 120):  # a LONG stall: 5x baseline for 100 steps
        eng.observe_step(i, 0.050)
    # the stall never becomes the new normal
    assert eng._step.baseline.mean == pytest.approx(0.010, rel=0.05)


def test_single_spike_not_flagged():
    eng = _engine()
    for i in range(30):
        eng.observe_step(i, 0.010)
    assert eng.observe_step(30, 0.5) == []   # one GC pause
    assert eng.observe_step(31, 0.010) == []
    assert eng.recent_findings() == []


def test_throughput_regression_and_exposed_growth():
    eng = _engine()
    for i in range(30):
        eng.observe_step(i, 0.010, units_per_s=3200.0,
                         exposed_comm_s=0.001)
    out = []
    for i in range(30, 40):  # throughput halves, exposed comm triples
        out += eng.observe_step(i, 0.010, units_per_s=1500.0,
                                exposed_comm_s=0.006)
    kinds = {f["kind"] for f in out}
    assert kinds == {"throughput_regression", "exposed_comm_growth"}


# -- persistent straggler ---------------------------------------------------

def _window(times):
    return {str(r): {"win_step_time": t} for r, t in times.items()}


def test_persistent_straggler_needs_same_rank_n_windows():
    eng = _engine()
    healthy = _window({0: 0.01, 1: 0.011, 2: 0.0105})
    for _ in range(10):
        assert eng.observe_fleet(healthy) == []
    # rank 2 turns slow; windows 1 and 2 accumulate, window 3 flags
    slow = _window({0: 0.01, 1: 0.011, 2: 0.05})
    assert eng.observe_fleet(slow) == []
    assert eng.observe_fleet(slow) == []
    out = eng.observe_fleet(slow)
    assert len(out) == 1 and out[0]["kind"] == "persistent_straggler"
    assert out[0]["rank"] == 2
    assert eng.observe_fleet(slow) == []  # hysteresis: same episode
    assert _counter(eng, "persistent_straggler") == 1


def test_rotating_straggler_not_flagged():
    """A different rank slowest each window is load noise, not a sick
    host — the trend detector must not fire."""
    eng = _engine()
    for i in range(12):
        slow_rank = i % 3
        times = {r: (0.05 if r == slow_rank else 0.01) for r in range(3)}
        assert eng.observe_fleet(_window(times)) == []
    assert eng.recent_findings() == []


def test_remesh_resets_baselines_keeps_findings():
    eng = _engine()
    for i in range(30):
        eng.observe_step(i, 0.010)
    for i in range(30, 40):
        eng.observe_step(i, 0.2)
    assert len(eng.recent_findings()) == 1
    eng.reset_baselines()
    assert len(eng.recent_findings()) == 1  # history survives
    # the new world runs 4x slower — legitimately; no flag
    for i in range(60):
        assert eng.observe_step(i, 0.040) == []


# -- ISSUE 7 acceptance: chaos stall window -> flagged, clean run -> not ----

def _run_telemetry_loop(steps):
    from horovod_tpu.train.callbacks import TelemetryCallback
    cb = TelemetryCallback(units_per_step=32, registry=Registry())
    for _ in range(steps):
        cb.on_step_begin()
        cb.on_step_end()
    return cb


def test_injected_slow_step_window_is_flagged_end_to_end(
        tmp_path, monkeypatch):
    """The acceptance path: a chaos `step` stall window makes
    hvd_anomaly_total{kind="step_time_drift"} increment on the DEFAULT
    registry, lands an `anomaly` flight event, and the autopsy bundle's
    summary names the degradation — with zero findings on a clean run
    of the same length."""
    from horovod_tpu import chaos
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.metrics import anomaly
    from horovod_tpu.metrics.registry import default_registry

    recorder().clear()
    plan = {"faults": [{"seam": "step", "kind": "stall",
                        "start": 30, "stop": 36, "stall_s": 0.15}]}
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps(plan))
    chaos.install(rank=0)
    try:
        _run_telemetry_loop(45)
    finally:
        monkeypatch.delenv("HVD_TPU_FAULT_PLAN")
        chaos.uninstall()
    findings = anomaly.recent_findings()
    kinds = [f["kind"] for f in findings]
    assert "step_time_drift" in kinds, findings
    counter = default_registry().get("hvd_anomaly_total",
                                     labels={"kind": "step_time_drift"})
    assert counter is not None and counter.value >= 1
    events = [e for e in recorder().events() if e["kind"] == "anomaly"]
    assert events, recorder().events()
    assert events[0]["detector"] == "step_time_drift"
    assert any(e.get("value", 0) > 0.1 for e in events)

    # the autopsy summary names the degradation
    from horovod_tpu.diagnostics.autopsy import write_autopsy
    bundle = write_autopsy(str(tmp_path / "bundle"), reason="test",
                           fetch_peers=False)
    summaries = [f for f in os.listdir(bundle)
                 if f.startswith("summary_rank")]
    assert summaries
    with open(os.path.join(bundle, summaries[0])) as f:
        summary = json.load(f)
    assert any(a["kind"] == "step_time_drift"
               for a in summary["anomalies"]), summary


def test_clean_run_of_same_length_flags_nothing():
    from horovod_tpu.metrics import anomaly
    _run_telemetry_loop(45)
    assert anomaly.recent_findings() == []


def test_anomaly_disabled_by_env(monkeypatch):
    from horovod_tpu.metrics import anomaly
    monkeypatch.setenv("HVD_TPU_ANOMALY", "0")
    anomaly.reset()
    assert anomaly.default_engine() is None
    assert anomaly.recent_findings() == []
    cb = _run_telemetry_loop(3)  # telemetry runs fine without the engine
    assert cb.anomaly_engine is None
