"""TF adapter tests (reference analog: test/parallel/test_tensorflow.py,
single-process slice; the multi-process path shares the core backend)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")


@pytest.fixture
def tfhvd(hvd):
    import horovod_tpu.tensorflow as tfhvd
    return tfhvd


def test_tf_allreduce(tfhvd):
    x = tf.constant([1.0, 2.0, 3.0])
    out = tfhvd.allreduce(x, op=tfhvd.Sum)
    np.testing.assert_allclose(out.numpy(), [1.0, 2.0, 3.0])


def test_tf_allgather_broadcast_alltoall(tfhvd):
    g = tfhvd.allgather(tf.eye(2))
    assert g.shape == (2, 2)
    b = tfhvd.broadcast(tf.constant([5.0]), root_rank=0)
    np.testing.assert_allclose(b.numpy(), [5.0])
    t = tfhvd.alltoall(tf.constant([[1.0], [2.0]]))
    assert t.shape == (2, 1)  # no splits arg -> bare tensor (reference)
    t2, rs = tfhvd.alltoall(tf.constant([[1.0], [2.0]]), splits=[2])
    assert t2.shape == (2, 1) and list(rs.numpy()) == [2]


def test_tf_distributed_gradient_tape(tfhvd):
    w = tf.Variable([[1.0], [2.0]])
    x = tf.constant([[3.0, 4.0]])
    with tfhvd.DistributedGradientTape(tf.GradientTape()) as tape:
        y = tf.reduce_sum(tf.matmul(x, w))
    (grad,) = tape.gradient(y, [w])
    np.testing.assert_allclose(grad.numpy(), [[3.0], [4.0]])


def test_tf_distributed_optimizer_trains(tfhvd):
    tf.random.set_seed(0)
    w = tf.Variable(tf.zeros((4, 1)))
    x = tf.constant(np.random.RandomState(0).randn(16, 4).astype(np.float32))
    target = tf.matmul(x, tf.constant([[1.0], [2.0], [3.0], [4.0]]))
    opt = tfhvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(learning_rate=0.1))
    losses = []
    for _ in range(100):
        with tf.GradientTape() as tape:
            loss = tf.reduce_mean((tf.matmul(x, w) - target) ** 2)
        grads = tape.gradient(loss, [w])
        opt.apply_gradients(zip(grads, [w]))
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.05


def test_tf_backward_passes_per_step(tfhvd):
    w = tf.Variable([0.0])
    opt = tfhvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(learning_rate=1.0),
        backward_passes_per_step=2)
    assert opt.apply_gradients([(tf.constant([1.0]), w)]) is None
    np.testing.assert_allclose(w.numpy(), [0.0])  # accumulating
    opt.apply_gradients([(tf.constant([3.0]), w)])
    np.testing.assert_allclose(w.numpy(), [-2.0])  # mean(1,3) applied


def test_tf_backward_passes_graph_mode(tfhvd):
    """backward_passes_per_step under tf.function (keras-compiled train
    steps): accumulation variables + tf.cond, not numpy on symbolic
    tensors (r2 review)."""
    w = tf.Variable([0.0])
    opt = tfhvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(learning_rate=1.0),
        backward_passes_per_step=2)

    @tf.function
    def step(g):
        return opt.apply_gradients([(g, w)])

    applied1 = step(tf.constant([1.0]))
    np.testing.assert_allclose(w.numpy(), [0.0])  # accumulating
    applied2 = step(tf.constant([3.0]))
    np.testing.assert_allclose(w.numpy(), [-2.0])  # mean(1,3) applied
    assert not bool(applied1) and bool(applied2)
    # next cycle accumulates again from zero
    step(tf.constant([5.0]))
    np.testing.assert_allclose(w.numpy(), [-2.0])
    step(tf.constant([7.0]))
    np.testing.assert_allclose(w.numpy(), [-8.0])  # -2 - mean(5,7)


def test_tf_sync_batch_norm(tfhvd):
    """TF-side SyncBatchNormalization (reference:
    tensorflow/sync_batch_norm.py): normalizes with batch moments in
    training, tracks unbiased running variance, uses running stats in eval."""
    rng = np.random.RandomState(0)
    x = tf.constant(rng.randn(16, 4).astype(np.float32))
    layer = tfhvd.SyncBatchNormalization(momentum=0.0)
    y = layer(x, training=True)
    np.testing.assert_allclose(np.asarray(y).mean(0), 0.0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(y).std(0), 1.0, atol=1e-2)
    n = x.shape[0]
    np.testing.assert_allclose(
        layer.moving_variance.numpy(),
        np.asarray(x).var(0) * n / (n - 1), rtol=1e-5)
    # eval path uses the running stats
    y2 = layer(x, training=False)
    assert np.all(np.isfinite(np.asarray(y2)))


def test_keras_optimizer_backward_passes(tfhvd):
    """hvd.keras.DistributedOptimizer must honor backward_passes_per_step
    (it used to silently ignore it), including under tf.function."""
    import horovod_tpu.keras as khvd
    w = tf.Variable([0.0])
    opt = khvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(learning_rate=1.0),
        backward_passes_per_step=2)

    @tf.function
    def step(g):
        opt.apply_gradients([(g, w)])

    step(tf.constant([1.0]))
    np.testing.assert_allclose(w.numpy(), [0.0])  # accumulating
    step(tf.constant([3.0]))
    np.testing.assert_allclose(w.numpy(), [-2.0])  # mean(1,3) applied


def test_tf_keras_elastic_state(tfhvd, tmp_path, monkeypatch):
    """TensorFlowKerasState snapshots/restores model+optimizer weights as
    one unit (reference: tensorflow/elastic.py)."""
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    model = tf.keras.Sequential(
        [tf.keras.layers.Input((3,)), tf.keras.layers.Dense(2)])
    opt = tf.keras.optimizers.SGD(learning_rate=0.1)
    model.compile(optimizer=opt, loss="mse")
    state = tfhvd.elastic.TensorFlowKerasState(model, opt, epoch=0,
                                               name="tfk")
    state.save()
    before = [w.copy() for w in model.get_weights()]
    model.set_weights([w + 1.0 for w in model.get_weights()])
    state.epoch = 4
    state.restore()
    for a, b in zip(model.get_weights(), before):
        np.testing.assert_allclose(a, b)
    assert state.epoch == 0
    state.sync()  # size 1: must be a no-op that doesn't fail
    # generation restart resume: fresh objects adopt the committed state
    model2 = tf.keras.Sequential(
        [tf.keras.layers.Input((3,)), tf.keras.layers.Dense(2)])
    state.epoch = 2
    state.save()
    state2 = tfhvd.elastic.TensorFlowKerasState(model2, None, epoch=0,
                                                name="tfk")
    assert state2.epoch == 2
    for a, b in zip(model2.get_weights(), before):
        np.testing.assert_allclose(a, b)


def test_tf_raw_variable_elastic_state(tfhvd, tmp_path, monkeypatch):
    """TensorFlowState syncs an explicit variable list (reference's
    non-Keras variant, tensorflow/elastic.py TensorFlowState)."""
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    v1 = tf.Variable([1.0, 2.0])
    v2 = tf.Variable([[3.0]])
    state = tfhvd.elastic.TensorFlowState([v1, v2], step=7, name="tfraw")
    state.save()
    v1.assign([9.0, 9.0])
    state.step = 0
    state.restore()
    np.testing.assert_allclose(v1.numpy(), [1.0, 2.0])
    assert state.step == 7
    state.sync()  # size 1 no-op


def test_tf_graph_mode_identity_ops(tfhvd):
    """size_op/rank_op/... resolve at EXECUTION time inside tf.function
    (reference: tensorflow/mpi_ops.py:361-440)."""
    @tf.function
    def g():
        return (tfhvd.size_op(), tfhvd.rank_op(), tfhvd.local_size_op(),
                tfhvd.local_rank_op(), tfhvd.process_set_included_op(0),
                tfhvd.process_set_included_op(99))

    assert [int(x) for x in g()] == [
        tfhvd.size(), tfhvd.rank(), tfhvd.local_size(),
        tfhvd.local_rank(), 1, tfhvd.PROCESS_SET_ERROR_UNKNOWN_SET]


def test_tensorflow_keras_alias_namespace(tfhvd):
    """Reference exposes both horovod.keras and horovod.tensorflow.keras;
    the alias must carry the full Keras adapter surface."""
    import horovod_tpu.tensorflow.keras as tk
    import horovod_tpu.keras as k
    assert tk.DistributedOptimizer is k.DistributedOptimizer
    assert tk.callbacks.BroadcastGlobalVariablesCallback is \
        k.callbacks.BroadcastGlobalVariablesCallback
    assert tk.rank() == tfhvd.rank() and tk.size() == tfhvd.size()


def test_tf_broadcast_variables(tfhvd):
    v = tf.Variable([7.0, 8.0])
    tfhvd.broadcast_variables([v], root_rank=0)
    np.testing.assert_allclose(v.numpy(), [7.0, 8.0])


def test_keras_callbacks_fit(tfhvd):
    """hvd.keras callbacks plugged into model.fit (reference analog:
    Keras callback tests)."""
    import horovod_tpu.keras as khvd
    model = tf.keras.Sequential(
        [tf.keras.layers.Dense(1, input_shape=(4,))])
    opt = khvd.DistributedOptimizer(
        tf.keras.optimizers.SGD(learning_rate=0.05))
    model.compile(optimizer=opt, loss="mse", run_eagerly=True)
    x = np.random.RandomState(0).randn(64, 4).astype(np.float32)
    y = x @ np.asarray([[1.0], [2.0], [3.0], [4.0]], np.float32)
    hist = model.fit(
        x, y, epochs=2, batch_size=16, verbose=0,
        callbacks=[khvd.callbacks.BroadcastGlobalVariablesCallback(0),
                   khvd.callbacks.MetricAverageCallback(),
                   khvd.callbacks.LearningRateWarmupCallback(
                       0.05, warmup_epochs=1, steps_per_epoch=4)])
    assert hist.history["loss"][-1] < hist.history["loss"][0]


def test_indexed_slices_passthrough_size1(tfhvd):
    """Sparse embedding grads (IndexedSlices) stay sparse through the tape
    and apply at world size 1 (the eager pass-through must not densify)."""
    emb = tf.Variable(np.zeros((4, 3), np.float32))
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(tf.nn.embedding_lookup(emb, [1, 1, 2]))
    g = tfhvd.DistributedGradientTape(tape).gradient(loss, [emb])[0]
    assert isinstance(g, tf.IndexedSlices)
    opt = tfhvd.DistributedOptimizer(tf.keras.optimizers.SGD(1.0))
    opt.apply_gradients([(g, emb)])
    got = emb.numpy()
    assert got[1, 0] == -2.0 and got[2, 0] == -1.0 and got[0, 0] == 0.0


def test_tape_single_variable_source(tfhvd):
    """sources may be a lone Variable (reference tape nest semantics):
    the result keeps the caller's structure — a tensor, not a list."""
    w = tf.Variable(np.ones((3, 2), np.float32))
    with tf.GradientTape() as tape:
        loss = tf.reduce_sum(w * w)
    g = tfhvd.DistributedGradientTape(tape).gradient(loss, w)
    assert not isinstance(g, (list, tuple))
    np.testing.assert_allclose(g.numpy(), 2 * np.ones((3, 2)), rtol=1e-6)


def test_accumulation_with_sparse_grads(tfhvd):
    """backward_passes_per_step with IndexedSlices grads: the accumulator
    densifies them instead of crashing (sparse stays sparse only on the
    no-accumulation path)."""
    emb = tf.Variable(np.zeros((4, 2), np.float32))
    opt = tfhvd.DistributedOptimizer(tf.keras.optimizers.SGD(1.0),
                                     backward_passes_per_step=2)
    for _ in range(2):
        with tf.GradientTape() as tape:
            loss = tf.reduce_sum(tf.nn.embedding_lookup(emb, [1, 2]))
        g = tape.gradient(loss, [emb])[0]
        opt.apply_gradients([(g, emb)])
    got = emb.numpy()
    assert got[1, 0] == -1.0 and got[2, 0] == -1.0 and got[0, 0] == 0.0


def test_keras_load_model_wraps_optimizer(tfhvd, tmp_path):
    """hvd.keras.load_model restores a saved model with its optimizer made
    distributed IN PLACE — the checkpointed slot state (Adam moments,
    iteration count) must survive the wrap (reference keras load_model)."""
    import horovod_tpu.keras as khvd
    model = tf.keras.Sequential([tf.keras.layers.Dense(1, input_shape=(4,))])
    model.compile(optimizer=tf.keras.optimizers.Adam(0.05), loss="mse",
                  run_eagerly=True)
    x = np.random.RandomState(0).randn(32, 4).astype(np.float32)
    y = x @ np.asarray([[1.0], [2.0], [3.0], [4.0]], np.float32)
    model.fit(x, y, epochs=1, batch_size=16, verbose=0)
    path = str(tmp_path / "m.keras")
    model.save(path)
    saved_slots = [np.asarray(v) for v in model.optimizer.variables]
    assert any(np.abs(s).sum() > 0 for s in saved_slots)  # moments moved

    loaded = khvd.load_model(path)
    assert type(loaded.optimizer).__name__ == "DistributedAdam"
    for got, want in zip(loaded.optimizer.variables, saved_slots):
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)
    hist = loaded.fit(x, y, epochs=2, batch_size=16, verbose=0)
    assert hist.history["loss"][-1] < hist.history["loss"][0]


def test_keras_elastic_callbacks(tfhvd, tmp_path, monkeypatch):
    """CommitStateCallback + Update{Batch,Epoch}StateCallback drive a
    keras fit with elastic state tracking (reference: _keras/elastic.py):
    commits happen per batch cadence, state.epoch counts globally."""
    monkeypatch.setenv("HVD_ELASTIC_CKPT", str(tmp_path))
    from horovod_tpu.tensorflow.elastic import (CommitStateCallback,
                                                TensorFlowKerasState,
                                                UpdateBatchStateCallback,
                                                UpdateEpochStateCallback)

    model = tf.keras.Sequential(
        [tf.keras.layers.Input((4,)), tf.keras.layers.Dense(1)])
    opt = tf.keras.optimizers.SGD(0.05)
    model.compile(optimizer=opt, loss="mse", run_eagerly=True)
    state = TensorFlowKerasState(model, opt, epoch=0, batch=0,
                                 name="kcb")
    commits = []
    orig_commit = state.commit
    state.commit = lambda: (commits.append(1), orig_commit())[1]

    x = np.random.RandomState(0).randn(32, 4).astype(np.float32)
    y = x @ np.asarray([[1.0], [2.0], [3.0], [4.0]], np.float32)
    # reference order: Update* first, Commit LAST so every commit
    # captures counters for the same batch/epoch
    model.fit(x, y, epochs=3, batch_size=8, verbose=0, callbacks=[
        UpdateBatchStateCallback(state),
        UpdateEpochStateCallback(state),
        CommitStateCallback(state, batches_per_commit=2)])

    assert state.epoch == 3          # global epochs tracked
    assert state.batch == 0          # reset at epoch end
    # 4 batches/epoch -> 2 cadence commits + 1 epoch-end commit, x3
    assert len(commits) == 9, commits


def test_batch_state_callback_resumed_epoch_shrink(tfhvd):
    """After a mid-epoch restore, on_epoch_begin shrinks params['steps']
    by the committed batch count (reference parity — honored by legacy
    loops, progbar-only on modern keras) and restores it at epoch end;
    state.batch counts completed batches within the current run, never
    overcounting (reference: _keras/elastic.py UpdateBatchStateCallbackImpl)."""
    import horovod_tpu.tensorflow.elastic as tfe

    class _State:
        batch = 30
    state = _State()
    cb = tfe.UpdateBatchStateCallback(state)
    cb.params = {"steps": 100}
    cb.on_epoch_begin(0)
    assert cb.params["steps"] == 70           # resumed epoch runs remainder
    cb.on_batch_end(0)
    assert state.batch == 1                   # within-run count: a commit
    cb.on_batch_end(1)                        # here may re-train batches on
    assert state.batch == 2                   # restore, but never skips any
    cb.on_epoch_end(0)
    assert cb.params["steps"] == 100          # later epochs run full length
    assert state.batch == 0
    cb.on_epoch_begin(1)
    assert cb.params["steps"] == 100          # no shrink without resume
    # unknown-cardinality fit: params['steps'] is None -> no shrink, no crash
    state2 = _State()
    cb2 = tfe.UpdateBatchStateCallback(state2)
    cb2.params = {"steps": None}
    cb2.on_epoch_begin(0)
    assert cb2.params["steps"] is None
    cb2.on_batch_end(49)
    assert state2.batch == 50


def test_keras_elastic_namespace(tfhvd):
    """horovod.keras.elastic / horovod.tensorflow.keras.elastic resolve
    here with the reference surface (run, KerasState, fit callbacks)."""
    import horovod_tpu.keras as khvd
    import horovod_tpu.tensorflow.keras as tkhvd
    for ns in (khvd.elastic, tkhvd.elastic):
        assert callable(ns.run)
        assert ns.KerasState is ns.TensorFlowKerasState
        assert callable(ns.CommitStateCallback)
        assert callable(ns.UpdateBatchStateCallback)
        assert callable(ns.UpdateEpochStateCallback)
