"""Minimal ray stand-in for exercising ``horovod_tpu.ray.RayExecutor``
without a Ray installation (reference analog: the Ray integration tests in
``test/integration/test_ray.py`` run against ``ray.init(local_mode=...)``;
this image has no ray, so the actor surface the executor actually touches
is reimplemented here over subprocesses + a framed-pipe RPC).

Surface implemented (exactly what ``horovod_tpu/ray/__init__.py`` uses):

- ``@ray.remote(num_cpus=...)`` on a class → ``.remote(*args)`` actor
  construction; actor method ``.remote(...)`` calls returning futures
- ``ray.get(future | [futures])``
- ``ray.kill(actor)``
- ``ray.nodes()`` (for ``RayHostDiscovery``) — returns ``_FAKE_NODES``,
  settable by the test

Each actor is a REAL subprocess (like a Ray worker): the class cell and
every call travel via cloudpickle, and method calls are dispatched
asynchronously — a future is created when ``.remote()`` is called and the
response is read only at ``ray.get``, so concurrent ``execute`` calls that
rendezvous in ``hvd.init()`` across actors make progress, exactly as on a
real Ray cluster.
"""

from __future__ import annotations

import os
import struct
import subprocess
import sys

_FAKE_NODES = []  # tests assign dicts shaped like ray.nodes() entries

_ACTOR_MAIN = r"""
import os, struct, sys
# protocol rides a dup of stdout; user-level prints go to stderr so they
# can never corrupt frames
proto_out = os.fdopen(os.dup(1), "wb")
os.dup2(2, 1)
# force the CPU JAX platform (this box's sitecustomize re-registers the
# real TPU platform from inside jax; unit-test actors must not touch it)
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import cloudpickle
proto_in = os.fdopen(0, "rb")

def read_frame():
    hdr = proto_in.read(4)
    if len(hdr) < 4:
        sys.exit(0)
    (n,) = struct.unpack(">I", hdr)
    return cloudpickle.loads(proto_in.read(n))

def write_frame(obj):
    blob = cloudpickle.dumps(obj)
    proto_out.write(struct.pack(">I", len(blob)) + blob)
    proto_out.flush()

cls, args, kwargs = read_frame()
obj = cls(*args, **kwargs)
while True:
    name, cargs, ckwargs = read_frame()
    try:
        write_frame(("ok", getattr(obj, name)(*cargs, **ckwargs)))
    except BaseException as e:  # report, keep serving
        write_frame(("err", f"{type(e).__name__}: {e}"))
"""


class _Future:
    def __init__(self, actor, index: int):
        self._actor = actor
        self._index = index

    def get(self):
        return self._actor._read_until(self._index)


class _Actor:
    def __init__(self, cls, args, kwargs):
        import cloudpickle

        # bufsize=0: reads must go straight to the pipe so select() in
        # _read_until never misses data parked in a Python-level buffer
        self._proc = subprocess.Popen(
            [sys.executable, "-c", _ACTOR_MAIN],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, bufsize=0,
            env=dict(os.environ))
        self._sent = 0
        self._received = 0
        self._results = {}
        self._write(cloudpickle.dumps((cls, args, kwargs)))

    def _write(self, blob: bytes) -> None:
        self._proc.stdin.write(struct.pack(">I", len(blob)) + blob)
        self._proc.stdin.flush()

    def _call(self, name, args, kwargs) -> _Future:
        import cloudpickle

        self._write(cloudpickle.dumps((name, args, kwargs)))
        fut = _Future(self, self._sent)
        self._sent += 1
        return fut

    def _read_exact(self, n: int, deadline: float) -> bytes:
        """Read exactly n bytes from the (unbuffered) actor pipe, failing
        at the deadline: a stalled actor (wedged rendezvous) must fail
        the test, not hang the pytest session."""
        import select
        import time

        buf = b""
        while len(buf) < n:
            remaining = deadline - time.time()
            if remaining <= 0 or not select.select(
                    [self._proc.stdout], [], [], remaining)[0]:
                self._kill()
                raise RuntimeError("fake ray actor call timed out")
            chunk = self._proc.stdout.read(n - len(buf))
            if not chunk:
                raise RuntimeError(
                    f"fake ray actor died (rc={self._proc.poll()})")
            buf += chunk
        return buf

    def _read_until(self, index: int, deadline_s: float = 180.0):
        import time

        import cloudpickle

        deadline = time.time() + deadline_s
        while self._received <= index:
            (n,) = struct.unpack(">I", self._read_exact(4, deadline))
            status, value = cloudpickle.loads(self._read_exact(n, deadline))
            self._results[self._received] = (status, value)
            self._received += 1
        status, value = self._results.pop(index)
        if status == "err":
            raise RuntimeError(f"fake ray actor call failed: {value}")
        return value

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        actor = self

        class _Method:
            @staticmethod
            def remote(*args, **kwargs):
                return actor._call(name, args, kwargs)

        return _Method()

    def _kill(self) -> None:
        if self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait(timeout=30)


class _RemoteClass:
    def __init__(self, cls):
        self._cls = cls

    def remote(self, *args, **kwargs):
        return _Actor(self._cls, args, kwargs)


def remote(*args, **kwargs):
    if len(args) == 1 and not kwargs and isinstance(args[0], type):
        return _RemoteClass(args[0])  # bare @ray.remote

    def deco(cls):
        return _RemoteClass(cls)

    return deco  # @ray.remote(num_cpus=...)


def get(x):
    if isinstance(x, (list, tuple)):
        return [f.get() for f in x]
    return x.get()


def kill(actor) -> None:
    actor._kill()


def nodes():
    return list(_FAKE_NODES)
