"""Deep-profiling subsystem tests (ISSUE 9, docs/OBSERVABILITY.md
"Deep profiling" / "Compile & memory observability" / "Re-mesh
timeline"):

* ProfileManager — step-windowed ``jax.profiler`` captures on CPU
  (non-empty bytes), size rotation, rate limiting, aborted-capture
  flush;
* recompile_storm — the detector unit battery (storm flagged with the
  offending function named; a shape-stable run stays clean) plus the
  real-jax integration;
* HBM gauges — sampling with a fake ``memory_stats`` (CPU reports
  none), min-merge across ranks, the hbm_growth slow-leak detector;
* re-mesh timeline — episode phases land as
  ``hvd_remesh_seconds{phase}``, flight spans and a history point;
* the END-TO-END ACCEPTANCE: a chaos-injected slow-step window on the
  8-device CPU mesh makes the anomaly engine fire and the
  ProfileManager autonomously write a non-empty bounded capture, with
  the ``profile_captured`` flight event and the capture path in the
  finding + autopsy summary — while a clean run of the same length
  captures nothing.
"""

import json
import os

import jax
import jax.numpy as jnp
import pytest

from horovod_tpu.metrics.registry import Registry
from horovod_tpu.profiling import compile_watch, memory
from horovod_tpu.profiling.manager import ProfileManager


@pytest.fixture(autouse=True)
def _fresh(tmp_path, monkeypatch):
    """Every test gets its own profile dir and fresh singletons."""
    import horovod_tpu.profiling as profiling
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.elastic import remesh
    from horovod_tpu.metrics import anomaly, timeseries
    monkeypatch.setenv("HVD_TPU_PROFILE_DIR", str(tmp_path / "prof"))
    profiling.reset()
    anomaly.reset()
    timeseries.reset()
    remesh.reset()
    recorder().clear()
    yield
    profiling.reset()
    anomaly.reset()
    timeseries.reset()
    remesh.reset()
    recorder().clear()


@jax.jit
def _work(x):
    return (x @ x).sum()


def _drive(mgr, steps, work=True):
    x = jnp.ones((32, 32))
    for i in range(1, steps + 1):
        mgr.on_step_begin(i)
        if work:
            _work(x).block_until_ready()
        mgr.on_step_end(i)


def _flight(kind):
    from horovod_tpu.diagnostics.flight_recorder import recorder
    return [e for e in recorder().events() if e["kind"] == kind]


# -- ProfileManager ----------------------------------------------------------

def test_capture_window_is_step_bounded_and_nonempty(tmp_path):
    mgr = ProfileManager(registry=Registry())
    info = mgr.request_capture(steps=2, reason="unit")
    assert info is not None and info["steps"] == 2
    _drive(mgr, 5)
    caps = mgr.recent_captures()
    assert len(caps) == 1, caps
    c = caps[0]
    assert c["steps"] == 2
    assert c["first_step"] == 1 and c["last_step"] == 2
    assert c["bytes"] > 0, "capture must contain real trace bytes"
    assert os.path.isdir(c["path"])
    evs = _flight("profile_captured")
    assert evs and evs[0]["path"] == c["path"]


def test_second_request_refused_while_pending_or_active():
    mgr = ProfileManager(registry=Registry())
    assert mgr.request_capture(steps=3) is not None
    assert mgr.request_capture(steps=3) is None  # pending
    mgr.on_step_begin(1)
    assert mgr.request_capture(steps=3) is None  # active
    assert mgr.dropped_requests == 2
    _drive(mgr, 3)
    # window closed: a new request is accepted again
    assert mgr.request_capture(steps=1) is not None


def test_request_during_trace_start_window_refused(monkeypatch):
    """The slot is claimed atomically with consuming the pending
    request: a request arriving while on_step_begin is still inside
    jax.profiler.start_trace must be refused, not accepted-then-lost."""
    mgr = ProfileManager(registry=Registry())
    seen = {}

    def _racing_start(path):
        # simulates an exporter/anomaly thread hitting the gap
        seen["racer"] = mgr.request_capture(steps=1, reason="racer")

    monkeypatch.setattr(mgr, "_start_trace", _racing_start)
    monkeypatch.setattr(mgr, "_stop_trace", lambda: None)
    assert mgr.request_capture(steps=1) is not None
    _drive(mgr, 2, work=False)
    assert seen["racer"] is None
    assert len(mgr.recent_captures()) == 1


def test_failed_trace_start_releases_slot(monkeypatch):
    mgr = ProfileManager(registry=Registry())

    def _broken_start(path):
        raise RuntimeError("profiler busy")

    monkeypatch.setattr(mgr, "_start_trace", _broken_start)
    assert mgr.request_capture(steps=1) is not None
    mgr.on_step_begin(1)
    mgr.on_step_end(1)
    assert mgr.status()["active"] is None
    assert mgr.recent_captures() == []
    # the slot is free again for a working capture
    monkeypatch.undo()
    assert mgr.request_capture(steps=1) is not None
    _drive(mgr, 2)
    assert len(mgr.recent_captures()) == 1


def test_finalize_racing_trace_start_cancels_cleanly(monkeypatch):
    """finalize_open_capture (autopsy/watchdog thread) landing between
    the claim and the trace start must not orphan a running trace: the
    unstarted record is dropped with nothing to flush, and the training
    thread closes the trace it just opened."""
    mgr = ProfileManager(registry=Registry())
    stopped = {"n": 0}

    def _racing_start(path):
        # the autopsy thread finalizes while start_trace is in flight
        assert mgr.finalize_open_capture("autopsy") is None

    monkeypatch.setattr(mgr, "_start_trace", _racing_start)
    monkeypatch.setattr(
        mgr, "_stop_trace",
        lambda: stopped.__setitem__("n", stopped["n"] + 1))
    assert mgr.request_capture(steps=1) is not None
    mgr.on_step_begin(1)
    mgr.on_step_end(1)
    assert stopped["n"] == 1  # the just-opened trace was closed
    assert mgr.recent_captures() == []
    assert mgr.status()["active"] is None
    # the manager still works afterwards
    monkeypatch.undo()
    assert mgr.request_capture(steps=1) is not None
    _drive(mgr, 2)
    assert len(mgr.recent_captures()) == 1


def test_failed_start_does_not_burn_anomaly_cooldown(monkeypatch):
    """The cooldown is charged when the trace STARTS: a capture that
    failed to open must leave the episode's window available."""
    mgr = ProfileManager(registry=Registry())
    monkeypatch.setenv("HVD_TPU_PROFILE_COOLDOWN_S", "3600")

    def _broken_start(path):
        raise RuntimeError("profiler busy")

    monkeypatch.setattr(mgr, "_start_trace", _broken_start)
    assert mgr.request_capture(steps=1, rate_limited=True) is not None
    mgr.on_step_begin(1)
    mgr.on_step_end(1)
    assert mgr.recent_captures() == []
    # the failed start left the cooldown unburned: re-arm works now
    monkeypatch.undo()
    monkeypatch.setenv("HVD_TPU_PROFILE_COOLDOWN_S", "3600")
    assert mgr.request_capture(steps=1, rate_limited=True) is not None
    _drive(mgr, 2)
    assert len(mgr.recent_captures()) == 1
    # ...and the successful start DID charge it
    assert mgr.request_capture(steps=1, rate_limited=True) is None


def test_anomaly_trigger_rate_limited(monkeypatch):
    mgr = ProfileManager(registry=Registry())
    monkeypatch.setenv("HVD_TPU_PROFILE_COOLDOWN_S", "3600")
    assert mgr.request_capture(steps=1, rate_limited=True) is not None
    _drive(mgr, 2)
    # inside the cooldown: the anomaly path is refused...
    assert mgr.request_capture(steps=1, rate_limited=True) is None
    # ...while an explicit on-demand request still goes through
    assert mgr.request_capture(steps=1, reason="debug") is not None
    monkeypatch.setenv("HVD_TPU_PROFILE_COOLDOWN_S", "0")
    _drive(mgr, 2)
    assert mgr.request_capture(steps=1, rate_limited=True) is not None


def test_retention_rotates_oldest_capture(tmp_path, monkeypatch):
    mgr = ProfileManager(registry=Registry())
    monkeypatch.setenv("HVD_TPU_PROFILE_COOLDOWN_S", "0")
    mgr.request_capture(steps=1, reason="first")
    _drive(mgr, 2)
    first = mgr.recent_captures()[0]["path"]
    # budget below one capture's size: the next capture evicts the first
    monkeypatch.setenv("HVD_TPU_PROFILE_MAX_BYTES", "1")
    mgr.request_capture(steps=1, reason="second")
    _drive(mgr, 2)
    caps = mgr.recent_captures()
    assert len(caps) == 2
    second = caps[-1]["path"]
    assert not os.path.exists(first), "oldest capture must rotate out"
    assert os.path.isdir(second), "newest capture is never deleted"


def test_finalize_open_capture_flushes_partial_window():
    mgr = ProfileManager(registry=Registry())
    mgr.request_capture(steps=100, reason="will_hang")
    mgr.on_step_begin(1)
    _work(jnp.ones((16, 16))).block_until_ready()
    rec = mgr.finalize_open_capture(reason="autopsy")
    assert rec is not None and rec["aborted"] == "autopsy"
    assert rec["bytes"] > 0
    assert mgr.recent_captures()[-1]["path"] == rec["path"]
    assert mgr.finalize_open_capture() is None  # idempotent


# -- recompile storm ---------------------------------------------------------

def _fresh_engine(monkeypatch):
    from horovod_tpu.metrics import anomaly
    anomaly.reset()
    return anomaly


def test_recompile_storm_unit_battery(monkeypatch):
    """Direct detector battery: same function recompiling past warmup
    flags (function named, re-flags only after another storm's worth),
    while many distinct functions compiling once stay clean."""
    anomaly = _fresh_engine(monkeypatch)
    monkeypatch.setenv("HVD_TPU_RECOMPILE_WARMUP", "2")
    monkeypatch.setenv("HVD_TPU_RECOMPILE_STORM", "3")
    compile_watch.reset_counts()
    # shape-stable world: 50 distinct functions, one compile each
    for i in range(50):
        compile_watch._note_compiling(f"stable_fn_{i}")
    assert anomaly.recent_findings() == []
    # one function recompiles: warmup 2 + storm 3 -> flag at the 5th
    for _ in range(4):
        compile_watch._note_compiling("drifting_step")
    assert anomaly.recent_findings() == []
    compile_watch._note_compiling("drifting_step")
    findings = anomaly.recent_findings()
    assert len(findings) == 1, findings
    f = findings[0]
    assert f["kind"] == "recompile_storm"
    assert f["function"] == "drifting_step"
    assert f["compiles"] == 5
    # hysteresis: the next 2 recompiles stay quiet, the 3rd re-flags
    compile_watch._note_compiling("drifting_step")
    compile_watch._note_compiling("drifting_step")
    assert len(anomaly.recent_findings()) == 1
    compile_watch._note_compiling("drifting_step")
    assert len(anomaly.recent_findings()) == 2


def test_recompile_storm_real_jax_names_function(monkeypatch):
    anomaly = _fresh_engine(monkeypatch)
    monkeypatch.setenv("HVD_TPU_PROFILE_ON_ANOMALY", "0")
    compile_watch.ensure_installed()
    compile_watch.reset_counts()

    @jax.jit
    def drifting_train_step(x):
        return x * 2

    for n in range(2, 10):  # shape drift: the classic silent killer
        drifting_train_step(jnp.ones(n))
    findings = anomaly.recent_findings()
    assert any(f["kind"] == "recompile_storm"
               and f["function"] == "drifting_train_step"
               for f in findings), findings
    # the flight event names it too
    evs = _flight("anomaly")
    assert any(e.get("detector") == "recompile_storm"
               and e.get("function") == "drifting_train_step"
               for e in evs), evs


def test_shape_stable_real_jax_run_is_clean(monkeypatch):
    anomaly = _fresh_engine(monkeypatch)
    compile_watch.ensure_installed()
    compile_watch.reset_counts()

    @jax.jit
    def stable_step(x):
        return x + 1

    for _ in range(30):
        stable_step(jnp.ones(8))
    assert not [f for f in anomaly.recent_findings()
                if f["kind"] == "recompile_storm"]


def test_compile_metrics_registered(monkeypatch):
    compile_watch.ensure_installed()
    compile_watch.reset_counts()

    @jax.jit
    def counted_fn(x):
        return x - 1

    counted_fn(jnp.ones(5))
    from horovod_tpu.metrics.registry import default_registry
    reg = default_registry()
    assert reg.get("hvd_compile_total").value >= 1
    assert reg.get("hvd_compile_cache_miss_total").value >= 1
    h = reg.get("hvd_compile_seconds", labels={"function": "counted_fn"})
    assert h is not None and h.count >= 1
    assert compile_watch.totals()["seconds_total"] > 0


def test_reinstall_after_uninstall_counts_each_compile_once():
    """uninstall cannot remove the jax.monitoring listener (no removal
    API) — a later ensure_installed must reuse it, not stack a second
    one that double-counts every compile."""
    import jax.monitoring
    compile_watch.ensure_installed()
    compile_watch.uninstall()
    compile_watch.ensure_installed()
    compile_watch.reset_counts()
    jax.monitoring.record_event_duration_secs(
        "/jax/core/compile/backend_compile_duration", 0.25)
    assert compile_watch.totals()["compiles"] == 1


def test_init_resets_storm_counts_per_generation(hvd):
    """Elastic re-init must drop per-function compile counts: every
    re-meshed world legitimately recompiles its jitted steps, and a
    long run would otherwise accumulate into a false recompile_storm
    (init resets anomaly baselines for exactly this reason)."""
    compile_watch.reset_counts()
    for _ in range(4):
        compile_watch._note_compiling("train_step")
    assert compile_watch.per_function_compiles()["train_step"] == 4
    hvd.shutdown()
    hvd.init()
    assert compile_watch.per_function_compiles().get("train_step") is None


def test_label_budget_resets_with_counts():
    """A long-lived process saturates the 32-label budget; reset_counts
    (tests, elastic re-init) must re-open it or every later function is
    attributed to 'other' forever."""
    compile_watch.reset_counts()
    for i in range(compile_watch.MAX_FUNCTION_LABELS + 5):
        compile_watch._function_label(f"saturating_fn_{i}")
    assert compile_watch._function_label("late_fn") == "other"
    compile_watch.reset_counts()
    assert compile_watch._function_label("late_fn") == "late_fn"


# -- HBM observability -------------------------------------------------------

def _fake_stats(in_use, peak, limit):
    return [{"bytes_in_use": in_use[i], "peak_bytes_in_use": peak[i],
             "bytes_limit": limit[i]} for i in range(len(in_use))]


def test_memory_gauges_from_fake_stats():
    reg = Registry()
    sampler = memory.MemorySampler(
        registry=reg,
        stats_fn=lambda: _fake_stats([100, 300], [400, 600],
                                     [1000, 900]))
    assert sampler.on_step(1) is None
    assert reg.get("hvd_hbm_bytes_in_use").value == 300   # max device
    assert reg.get("hvd_hbm_peak_bytes").value == 600     # max device
    assert reg.get("hvd_hbm_limit_bytes").value == 900    # min device
    # margin: min over devices of limit - peak = min(600, 300) = 300
    assert reg.get("hvd_hbm_oom_margin_bytes").value == 300


def test_cpu_without_stats_registers_nothing():
    reg = Registry()
    sampler = memory.MemorySampler(registry=reg, stats_fn=lambda: [])
    for i in range(3):
        assert sampler.on_step(i) is None
    assert reg.get("hvd_hbm_bytes_in_use") is None
    assert sampler._dead  # stopped asking after first contact


def test_transient_stats_failure_keeps_polling():
    """A failed first read (stats_fn -> None, the device_stats error
    signature) must not latch the sampler dead — HBM observability
    comes back when the backend recovers."""
    reg = Registry()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            return None  # transient PJRT error at step 1
        return _fake_stats([100], [200], [1000])

    sampler = memory.MemorySampler(registry=reg, stats_fn=flaky)
    assert sampler.on_step(1) is None
    assert not sampler._dead
    sampler.on_step(2)
    assert reg.get("hvd_hbm_bytes_in_use").value == 100.0


def test_statless_after_transient_error_still_goes_quiet():
    """None (error) at step 1 then clean [] at step 2: no stats were
    ever seen, so the sampler still latches dead — the quiet-mode
    guarantee is 'never saw stats', not 'first sample only'."""
    reg = Registry()
    seq = iter([None, [], []])
    sampler = memory.MemorySampler(registry=reg,
                                   stats_fn=lambda: next(seq))
    sampler.on_step(1)
    assert not sampler._dead
    sampler.on_step(2)
    assert sampler._dead


def test_min_gauge_merges_min_across_ranks():
    r1, r2 = Registry(), Registry()
    r1.gauge("hvd_hbm_oom_margin_bytes", agg="min").set(500)
    r2.gauge("hvd_hbm_oom_margin_bytes", agg="min").set(200)
    merged = Registry.merge([r1.snapshot(), r2.snapshot()])
    assert merged["hvd_hbm_oom_margin_bytes"]["value"] == 200


def test_hbm_growth_detector_flags_slow_leak():
    det = memory.HbmGrowthDetector(window=5, windows=3, min_frac=0.01)
    findings = []
    b = 1000.0
    for step in range(200):
        if step % 5 == 0:
            b *= 1.05  # +5% per window: a steady leak
        f = det.observe(b)
        if f:
            findings.append(f)
    assert findings, "a steady leak must flag"
    assert findings[0]["kind"] == "hbm_growth"
    assert findings[0]["growth_ratio"] > 1.0
    assert len(findings) == 1, "one finding per episode"


def test_hbm_flat_usage_is_clean():
    det = memory.HbmGrowthDetector(window=5, windows=3, min_frac=0.01)
    import random
    rng = random.Random(3)
    for _ in range(300):  # jittery but flat
        assert det.observe(1000 * (1 + 0.02 * (rng.random() - .5))) is None


# -- /debug/profile endpoint -------------------------------------------------

def test_debug_profile_endpoint_arms_capture():
    from urllib.request import urlopen

    from horovod_tpu.metrics.exporter import MetricsExporter
    from horovod_tpu.profiling import default_manager
    exp = MetricsExporter(port=0)
    exp.start()
    try:
        body = urlopen(f"http://127.0.0.1:{exp.port}/debug/profile"
                       "?steps=2", timeout=5).read()
        doc = json.loads(body)
        assert doc["started"] is True and doc["steps"] == 2
        # second request while pending: refused, status says why
        doc2 = json.loads(urlopen(
            f"http://127.0.0.1:{exp.port}/debug/profile?steps=2",
            timeout=5).read())
        assert doc2["started"] is False
        assert doc2["status"]["pending"] is not None
        # the armed window opens and closes on the step seam
        mgr = default_manager()
        _drive(mgr, 3)
        caps = mgr.recent_captures()
        assert caps and caps[0]["path"] == doc["path"]
        assert caps[0]["bytes"] > 0
    finally:
        exp.stop()


# -- re-mesh timeline --------------------------------------------------------

def test_remesh_episode_lands_histograms_flight_and_history():
    import time as _time

    from horovod_tpu.elastic import remesh
    from horovod_tpu.metrics import timeseries
    from horovod_tpu.metrics.registry import default_registry
    remesh.begin("internal_error", old_size=3)
    with remesh.phase("failure_detect"):
        _time.sleep(0.01)
    with remesh.phase("drain"):
        pass
    with remesh.phase("rendezvous"):
        pass
    with remesh.phase("rebuild"):
        pass
    with remesh.phase("restore"):
        pass
    remesh.mark_recovered(new_size=2, generation=7)
    assert remesh.current() is not None
    remesh.note_step_end(1)  # first completed step closes the episode
    assert remesh.current() is None
    reg = default_registry()
    for phase in ("failure_detect", "drain", "rendezvous", "rebuild",
                  "restore", "first_step"):
        h = reg.get("hvd_remesh_seconds", labels={"phase": phase})
        assert h is not None and h.count >= 1, phase
    assert reg.get("hvd_remesh_total").value >= 1
    spans = _flight("remesh_phase")
    assert {e["phase"] for e in spans} >= {"failure_detect", "drain",
                                           "restore"}
    done = _flight("remesh_complete")
    assert done and done[-1]["old_size"] == 3 \
        and done[-1]["new_size"] == 2
    # the history point renders in the CLI's remesh table
    pts = timeseries.recorder().ring.points()
    remesh_pts = [p for p in pts if "remesh" in p]
    assert remesh_pts and remesh_pts[-1]["trigger"] == "internal_error"
    from horovod_tpu.metrics.__main__ import render_remesh_table
    table = render_remesh_table(remesh_pts)
    assert "internal_error" in table and "failure_detect" in table


def test_abandoned_episode_skips_histograms_keeps_flight():
    """Partial phase times from an abandoned recovery (a retry storm)
    must not smear the regression-gateable hvd_remesh_seconds
    distribution; the evidence survives as a remesh_abandoned flight
    event."""
    import time
    from horovod_tpu.elastic import remesh
    from horovod_tpu.metrics.registry import default_registry
    reg = default_registry()

    def _counts():
        h = reg.get("hvd_remesh_seconds",
                    labels={"phase": "failure_detect"})
        c = reg.get("hvd_remesh_total")
        return (h.count if h else 0), (c.value if c else 0)

    before = _counts()
    remesh.begin("internal_error", old_size=3)
    with remesh.phase("failure_detect"):
        time.sleep(0.001)
    # a second failure before recovery: the first episode is abandoned
    remesh.begin("internal_error", old_size=3)
    assert _counts() == before
    assert _flight("remesh_abandoned")
    remesh.reset()


def test_same_world_retry_closes_spans_without_episode():
    """A transient failure that resolves into the SAME world is not a
    re-mesh episode — no histograms, no hvd_remesh_total — but the
    spans already emitted live get a remesh_retry terminal marker."""
    from horovod_tpu.elastic import remesh
    from horovod_tpu.metrics.registry import default_registry
    reg = default_registry()
    c = reg.get("hvd_remesh_total")
    before = c.value if c else 0
    remesh.begin("internal_error", old_size=3)
    with remesh.phase("drain"):
        pass
    remesh.note_same_world_retry()
    assert remesh.current() is None
    c = reg.get("hvd_remesh_total")
    assert (c.value if c else 0) == before
    retries = _flight("remesh_retry")
    assert retries and retries[-1]["trigger"] == "internal_error"


def test_remesh_noop_outside_episode():
    from horovod_tpu.elastic import remesh
    with remesh.phase("drain"):
        pass  # pass-through, nothing recorded
    remesh.note_step_end(1)
    assert not _flight("remesh_phase")


# -- CLI rendering -----------------------------------------------------------

def test_top_renders_hbm_and_compile_columns():
    from horovod_tpu.metrics.__main__ import render_top
    series = {
        "hvd_fleet_size": 2.0, "hvd_fleet_ranks_reporting": 2.0,
        "hvd_hbm_bytes_in_use": 6 * 2**30,
        "hvd_hbm_peak_bytes": 7 * 2**30,
        "hvd_hbm_limit_bytes": 16 * 2**30,
        "hvd_hbm_oom_margin_bytes": 9 * 2**30,
        "hvd_compile_total": 12.0,
        "hvd_compile_cache_miss_total": 14.0,
        'hvd_compile_seconds_sum{function="step"}': 33.5,
        "hvd_remesh_total": 2.0,
        'hvd_remesh_seconds_sum{phase="drain"}': 1.5,
    }
    out = render_top(series, "test")
    assert "hbm" in out and "6.0GiB" in out and "9.0GiB" in out
    assert "compiles" in out and "12" in out and "14 cache misses" in out
    assert "re-meshes" in out and "2 (" in out


# -- end-to-end acceptance ---------------------------------------------------

def _telemetry_loop_with_work(steps):
    """A telemetry loop doing REAL device work on the 8-device mesh so
    an auto-fired capture has something to trace."""
    from horovod_tpu.train.callbacks import TelemetryCallback
    cb = TelemetryCallback(units_per_step=32, registry=Registry())
    x = jnp.ones((8, 16, 16))
    devs = jax.devices()
    y = jax.device_put(x, jax.sharding.PositionalSharding(
        devs).reshape(8, 1, 1))
    step = jax.jit(lambda a: (a @ a).sum())
    for _ in range(steps):
        cb.on_step_begin()
        step(y).block_until_ready()
        cb.on_step_end()
    return cb


def test_acceptance_chaos_stall_fires_autonomous_capture(
        tmp_path, monkeypatch):
    """ISSUE 9 acceptance: chaos slow-step window -> anomaly finding ->
    ProfileManager autonomously writes a non-empty bounded capture;
    `profile_captured` flight event recorded; capture path in the
    finding and the autopsy summary."""
    from horovod_tpu import chaos
    from horovod_tpu.metrics import anomaly
    from horovod_tpu.profiling import default_manager

    monkeypatch.setenv("HVD_TPU_PROFILE_STEPS", "3")
    plan = {"faults": [{"seam": "step", "kind": "stall",
                        "start": 30, "stop": 36, "stall_s": 0.15}]}
    monkeypatch.setenv("HVD_TPU_FAULT_PLAN", json.dumps(plan))
    chaos.install(rank=0)
    try:
        _telemetry_loop_with_work(45)
    finally:
        monkeypatch.delenv("HVD_TPU_FAULT_PLAN")
        chaos.uninstall()

    findings = anomaly.recent_findings()
    drift = [f for f in findings if f["kind"] == "step_time_drift"]
    assert drift, findings
    caps = default_manager().recent_captures()
    assert len(caps) == 1, caps
    c = caps[0]
    assert c["bytes"] > 0, "the autonomous capture must be non-empty"
    assert c["steps"] == 3
    assert c["reason"] == "anomaly:step_time_drift"
    assert os.path.isdir(c["path"])
    # the finding carries the capture path (same dict the engine keeps)
    assert drift[0].get("profile") == c["path"], drift
    evs = _flight("profile_captured")
    assert evs and evs[0]["path"] == c["path"]

    # the autopsy summary ships both the anomaly and the capture path
    from horovod_tpu.diagnostics.autopsy import write_autopsy
    bundle = write_autopsy(str(tmp_path / "bundle"), reason="test",
                           fetch_peers=False)
    summaries = [f for f in os.listdir(bundle)
                 if f.startswith("summary_rank")]
    with open(os.path.join(bundle, summaries[0])) as f:
        summary = json.load(f)
    assert any(a["kind"] == "step_time_drift"
               for a in summary["anomalies"]), summary
    assert any(p["path"] == c["path"]
               for p in summary["profiles"]), summary


def test_acceptance_clean_run_captures_nothing(tmp_path):
    from horovod_tpu.metrics import anomaly
    from horovod_tpu.profiling import default_manager, profile_dir
    _telemetry_loop_with_work(45)
    assert anomaly.recent_findings() == []
    assert default_manager().recent_captures() == []
    assert not os.path.isdir(profile_dir()) or \
        os.listdir(profile_dir()) == []
