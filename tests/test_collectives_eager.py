"""Eager collective semantics, single-process (reference analog:
test/parallel/test_torch.py collective tests degeneratet to one rank)."""

import numpy as np
import pytest
import jax.numpy as jnp


def test_allreduce_identity(hvd):
    x = jnp.arange(8.0)
    out = hvd.allreduce(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


def test_allreduce_ops(hvd):
    x = jnp.ones((4, 4))
    for op in (hvd.Sum, hvd.Average, hvd.Min, hvd.Max, hvd.Product):
        out = hvd.allreduce(x, op=op)
        np.testing.assert_allclose(np.asarray(out), np.ones((4, 4)))


def test_allreduce_prescale_postscale(hvd):
    x = jnp.ones(4)
    out = hvd.allreduce(x, op=hvd.Sum, prescale_factor=2.0,
                        postscale_factor=3.0)
    np.testing.assert_allclose(np.asarray(out), 6.0 * np.ones(4))


def test_allreduce_average_and_op_conflict(hvd):
    with pytest.raises(ValueError):
        hvd.allreduce(jnp.ones(2), average=True, op=hvd.Sum)


def test_grouped_allreduce(hvd):
    xs = [jnp.ones(3), jnp.arange(4.0)]
    outs = hvd.grouped_allreduce(xs, op=hvd.Sum)
    assert len(outs) == 2
    np.testing.assert_allclose(np.asarray(outs[1]), np.arange(4.0))


def test_allgather(hvd):
    x = jnp.arange(6.0).reshape(3, 2)
    out = hvd.allgather(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_broadcast(hvd):
    x = jnp.arange(4.0)
    out = hvd.broadcast(x, root_rank=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    with pytest.raises(ValueError):
        hvd.broadcast(x, root_rank=1)  # out of range for size 1


def test_alltoall(hvd):
    x = jnp.arange(10.0)
    out, recv_splits = hvd.alltoall(x, splits=[10])
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    assert list(np.asarray(recv_splits)) == [10]


def test_async_handles(hvd):
    h = hvd.allreduce_async(jnp.ones(2), op=hvd.Sum)
    assert hvd.poll(h)
    out = hvd.synchronize(h)
    np.testing.assert_allclose(np.asarray(out), np.ones(2))


def test_join_barrier(hvd):
    assert hvd.join() == 0
    hvd.barrier()
