"""Eager collective semantics, single-process (reference analog:
test/parallel/test_torch.py collective tests degenerated to one rank).
The multi-rank depth matrix lives in matrix_worker.py, launched by
test_core_multiprocess.py over both backends."""

import numpy as np
import pytest
import jax.numpy as jnp

import ml_dtypes

DTYPES = [np.uint8, np.int8, np.int32, np.int64, np.float16,
          ml_dtypes.bfloat16, np.float32, np.float64, np.bool_]
SHAPES = [(), (0,), (1,), (7, 3), (256,)]


@pytest.mark.parametrize("dtype", DTYPES)
@pytest.mark.parametrize("shape", SHAPES)
def test_allreduce_dtype_shape_identity(hvd, dtype, shape):
    """Size-1 allreduce is identity for every dtype x shape class, and the
    result dtype must match the input dtype exactly."""
    n = int(np.prod(shape, dtype=np.int64))
    x = (np.arange(n, dtype=np.int64) % 2).reshape(shape).astype(dtype)
    for op in (hvd.Sum, hvd.Min, hvd.Max):
        out = np.asarray(hvd.allreduce(x, op=op))
        assert out.dtype == np.dtype(dtype), (op, out.dtype)
        np.testing.assert_array_equal(out, x)


@pytest.mark.parametrize("dtype", [np.float16, ml_dtypes.bfloat16,
                                   np.float32, np.float64])
def test_allreduce_average_identity_floats(hvd, dtype):
    x = np.arange(6, dtype=np.float64).astype(dtype)
    out = np.asarray(hvd.allreduce(x, op=hvd.Average))
    assert out.dtype == np.dtype(dtype)
    np.testing.assert_array_equal(out, x)


def test_fractional_int_scale_rejected(hvd):
    x = np.arange(4, dtype=np.int32)
    with pytest.raises(ValueError):
        hvd.allreduce(x, op=hvd.Sum, prescale_factor=0.5)
    with pytest.raises(ValueError):
        hvd.grouped_allreduce([x], op=hvd.Sum, prescale_factor=0.5)


def test_allreduce_identity(hvd):
    x = jnp.arange(8.0)
    out = hvd.allreduce(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


def test_allreduce_ops(hvd):
    x = jnp.ones((4, 4))
    for op in (hvd.Sum, hvd.Average, hvd.Min, hvd.Max, hvd.Product):
        out = hvd.allreduce(x, op=op)
        np.testing.assert_allclose(np.asarray(out), np.ones((4, 4)))


def test_allreduce_prescale_postscale(hvd):
    x = jnp.ones(4)
    out = hvd.allreduce(x, op=hvd.Sum, prescale_factor=2.0,
                        postscale_factor=3.0)
    np.testing.assert_allclose(np.asarray(out), 6.0 * np.ones(4))


def test_allreduce_average_and_op_conflict(hvd):
    with pytest.raises(ValueError):
        hvd.allreduce(jnp.ones(2), average=True, op=hvd.Sum)


def test_grouped_allreduce(hvd):
    xs = [jnp.ones(3), jnp.arange(4.0)]
    outs = hvd.grouped_allreduce(xs, op=hvd.Sum)
    assert len(outs) == 2
    np.testing.assert_allclose(np.asarray(outs[1]), np.arange(4.0))


def test_allgather(hvd):
    x = jnp.arange(6.0).reshape(3, 2)
    out = hvd.allgather(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_broadcast(hvd):
    x = jnp.arange(4.0)
    out = hvd.broadcast(x, root_rank=0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    with pytest.raises(ValueError):
        hvd.broadcast(x, root_rank=1)  # out of range for size 1


def test_alltoall(hvd):
    x = jnp.arange(10.0)
    out, recv_splits = hvd.alltoall(x, splits=[10])
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
    assert list(np.asarray(recv_splits)) == [10]


def test_async_handles(hvd):
    h = hvd.allreduce_async(jnp.ones(2), op=hvd.Sum)
    assert hvd.poll(h)
    out = hvd.synchronize(h)
    np.testing.assert_allclose(np.asarray(out), np.ones(2))


def test_join_barrier(hvd):
    assert hvd.join() == 0
    hvd.barrier()
