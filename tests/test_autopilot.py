"""Autopilot battery (ISSUE 12; docs/OBSERVABILITY.md "Autopilot"):
policy-spec validation, the policy engine's gate pipeline (hysteresis,
cooldown, action budget, SLO gates) driven through BOTH finding paths
— the engine's native ``_flag`` detectors and the external
``report_finding()`` seam — observe-vs-act decision parity, the
four-channel audit trail (metrics, flight, JSONL + CLI, autopsy), the
driver's ``action/`` scope validation, and the (slow) end-to-end
acceptance pair: a chaos-injected persistent straggler drained and
replaced autonomously under ``act``, with the IDENTICAL decision
recorded and nothing acted under ``observe``."""

import io
import json
import os
import socket
import sys
import textwrap
import time
from contextlib import redirect_stdout

import pytest

from horovod_tpu import autopilot
from horovod_tpu.autopilot import actions as ap_actions
from horovod_tpu.autopilot.engine import PolicyEngine
from horovod_tpu.autopilot.policy import (ACTIONS, AutopilotError, Policy,
                                          default_policies,
                                          load_policies_from_env,
                                          parse_policies)
from horovod_tpu.metrics.registry import Registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_singletons(monkeypatch):
    from horovod_tpu.diagnostics.flight_recorder import recorder
    from horovod_tpu.metrics import anomaly, timeseries
    monkeypatch.delenv("HVD_TPU_AUTOPILOT", raising=False)
    monkeypatch.delenv("HVD_TPU_AUTOPILOT_POLICY", raising=False)
    monkeypatch.delenv("HVD_TPU_OBS_DIR", raising=False)
    # manufactured findings must not arm real device-trace captures
    monkeypatch.setenv("HVD_TPU_PROFILE_ON_ANOMALY", "0")
    autopilot.reset()
    anomaly.reset()
    timeseries.reset()
    recorder().clear()
    yield
    autopilot.reset()
    anomaly.reset()
    timeseries.reset()


def _counter(reg, name, **labels):
    c = reg.get(name, labels=labels or None)
    return c.value if c is not None else 0.0


# -- policy spec validation --------------------------------------------------

def test_parse_minimal_policy_doc():
    ps = parse_policies(json.dumps({"policies": [
        {"name": "p", "finding": "persistent_straggler",
         "action": "drain_and_replace"}]}))
    assert len(ps) == 1
    assert ps[0].cooldown_s == 300.0 and ps[0].hysteresis == 1
    assert ps[0].needs_driver()


def test_unknown_keys_rejected():
    with pytest.raises(AutopilotError, match="unknown keys"):
        parse_policies(json.dumps({"policies": [
            {"name": "p", "finding": "x", "action": "retune",
             "cooldwn_s": 1}]}))
    with pytest.raises(AutopilotError, match="unknown document keys"):
        parse_policies(json.dumps({"policies": [], "polices": []}))


def test_unknown_action_rejected():
    with pytest.raises(AutopilotError, match="unknown action"):
        parse_policies(json.dumps({"policies": [
            {"name": "p", "finding": "x", "action": "reboot_planet"}]}))


def test_duplicate_names_rejected():
    doc = {"policies": [
        {"name": "p", "finding": "a", "action": "retune"},
        {"name": "p", "finding": "b", "action": "freeze_alert"}]}
    with pytest.raises(AutopilotError, match="duplicate policy names"):
        parse_policies(json.dumps(doc))


def test_bad_numbers_rejected():
    for bad in ({"cooldown_s": -1}, {"hysteresis": 0}, {"max_actions": 0},
                {"window_s": 0}, {"horizon_steps": 0},
                {"max_margin_frac": 1.5}, {"cooldown_s": "soon"}):
        doc = {"policies": [dict(
            {"name": "p", "finding": "x", "action": "retune"}, **bad)]}
        with pytest.raises(AutopilotError):
            parse_policies(json.dumps(doc))


def test_malformed_json_rejected():
    with pytest.raises(AutopilotError, match="not valid JSON"):
        parse_policies('{"policies": [')


def test_env_inline_and_file_loading(tmp_path, monkeypatch):
    doc = {"policies": [{"name": "only", "finding": "x",
                         "action": "freeze_alert"}]}
    monkeypatch.setenv("HVD_TPU_AUTOPILOT_POLICY", json.dumps(doc))
    assert [p.name for p in load_policies_from_env()] == ["only"]
    path = tmp_path / "pol.json"
    path.write_text(json.dumps(doc))
    monkeypatch.setenv("HVD_TPU_AUTOPILOT_POLICY", str(path))
    assert [p.name for p in load_policies_from_env()] == ["only"]
    monkeypatch.setenv("HVD_TPU_AUTOPILOT_POLICY", str(tmp_path / "nope"))
    with pytest.raises(AutopilotError, match="unreadable"):
        load_policies_from_env()


def test_default_policies_cover_every_remediation():
    """The shipped set: the four ISSUE 12 remediations, the two ISSUE
    13 data-plane integrity ones (quarantine + rollback), the ISSUE 14
    serving SLO scale-out, and the ISSUE 18 rollout promote/rollback
    pair (both gating on the same rollout_verdict finding)."""
    ps = default_policies()
    assert {p.action for p in ps} == set(ACTIONS)
    assert {p.finding for p in ps} == {
        "persistent_straggler", "hbm_growth", "recompile_storm",
        "world_changed", "replica_divergence", "grad_nonfinite",
        "slo_breach", "rollout_verdict"}
    # unset env -> the default set
    assert [p.name for p in load_policies_from_env()] == \
        [p.name for p in ps]


def test_mode_knob(monkeypatch):
    assert autopilot.mode() == "observe"  # the default
    monkeypatch.setenv("HVD_TPU_AUTOPILOT", "act")
    assert autopilot.mode() == "act"
    monkeypatch.setenv("HVD_TPU_AUTOPILOT", "bogus")
    assert autopilot.mode() == "observe"  # safe fallback, warned
    monkeypatch.setenv("HVD_TPU_AUTOPILOT", "off")
    assert autopilot.mode() == "off"
    assert autopilot.default_engine() is None
    assert autopilot.on_finding({"kind": "persistent_straggler"}) == []


def test_engine_identity_follows_rank_across_reinit(tmp_path,
                                                    monkeypatch):
    """Review hardening: the engine survives elastic re-inits (its
    cooldown/budget state must persist), but a re-mesh can renumber
    this worker — decisions and the JSONL filename must carry the
    CURRENT rank, like every other channel."""
    monkeypatch.setenv("HVD_TPU_AUTOPILOT", "observe")
    monkeypatch.setenv("HVD_TPU_AUTOPILOT_POLICY", json.dumps(
        {"policies": [{"name": "p", "finding": "k",
                       "action": "freeze_alert", "cooldown_s": 0}]}))
    monkeypatch.setenv("HVD_TPU_OBS_DIR", str(tmp_path))
    monkeypatch.setenv("HVD_TPU_RANK", "2")
    autopilot.reset()
    eng = autopilot.ensure_engine()
    autopilot.on_finding({"kind": "k"})
    assert eng.recent_decisions()[-1]["rank"] == 2
    # the re-mesh renumbered us; hvd.init re-arms the SAME engine
    monkeypatch.setenv("HVD_TPU_RANK", "1")
    assert autopilot.ensure_engine() is eng
    autopilot.on_finding({"kind": "k"})
    assert eng.recent_decisions()[-1]["rank"] == 1
    assert (tmp_path / "actions_rank2.jsonl").exists()
    assert (tmp_path / "actions_rank1.jsonl").exists()


def test_ensure_engine_is_the_loud_path(monkeypatch):
    monkeypatch.setenv("HVD_TPU_AUTOPILOT_POLICY", '{"policies": [')
    assert autopilot.default_engine() is None  # quiet path degrades
    with pytest.raises(AutopilotError):
        autopilot.ensure_engine()  # hvd.init path fails the job loudly


# -- the gate pipeline -------------------------------------------------------

def _engine(policies, mode="observe"):
    return PolicyEngine(policies=policies, registry=Registry(),
                        mode=mode, rank=0)


def test_decision_recorded_with_metrics_and_flight():
    from horovod_tpu.diagnostics.flight_recorder import recorder
    eng = _engine([Policy(name="p", finding="k", action="freeze_alert",
                          cooldown_s=0.0)])
    out = eng.on_finding({"kind": "k", "function": "f"})
    assert len(out) == 1 and out[0]["outcome"] == "dry_run"
    assert _counter(eng._reg, "hvd_autopilot_decisions_total",
                    policy="p", outcome="dry_run") == 1
    events = [e for e in recorder().events()
              if e["kind"] == "autopilot_decision"]
    assert events and events[-1]["policy"] == "p"
    assert events[-1]["outcome"] == "dry_run"
    assert eng.recent_decisions()[-1]["action"] == "freeze_alert"
    # no policy subscribes to this kind: no decision
    assert eng.on_finding({"kind": "unrelated"}) == []


def test_cooldown_suppresses_then_rearms():
    eng = _engine([Policy(name="p", finding="k", action="freeze_alert",
                          cooldown_s=0.2, max_actions=10,
                          window_s=3600)])
    assert eng.on_finding({"kind": "k"})[0]["outcome"] == "dry_run"
    d = eng.on_finding({"kind": "k"})[0]
    assert d["outcome"] == "suppressed" and d["reason"] == "cooldown"
    assert d["gate"]["cooldown_remaining_s"] >= 0
    time.sleep(0.25)
    assert eng.on_finding({"kind": "k"})[0]["outcome"] == "dry_run"


def test_hysteresis_needs_consecutive_findings():
    eng = _engine([Policy(name="p", finding="k", action="freeze_alert",
                          hysteresis=3, cooldown_s=0.0)])
    for expected in ("suppressed", "suppressed", "dry_run"):
        d = eng.on_finding({"kind": "k"})[0]
        assert d["outcome"] == expected, d
        if expected == "suppressed":
            assert d["reason"] == "hysteresis"


def test_budget_exhaustion_within_window():
    eng = _engine([Policy(name="p", finding="k", action="freeze_alert",
                          cooldown_s=0.0, max_actions=2,
                          window_s=3600)])
    assert eng.on_finding({"kind": "k"})[0]["outcome"] == "dry_run"
    assert eng.on_finding({"kind": "k"})[0]["outcome"] == "dry_run"
    d = eng.on_finding({"kind": "k"})[0]
    assert d["outcome"] == "suppressed" and d["reason"] == "budget"
    assert d["gate"]["actions_in_window"] == 2


def test_key_field_scopes_the_gates_per_value():
    eng = _engine([Policy(name="p", finding="recompile_storm",
                          action="freeze_alert", hysteresis=2,
                          cooldown_s=3600, key_field="function")])
    # two functions storm interleaved: each needs ITS OWN second report
    assert eng.on_finding({"kind": "recompile_storm",
                           "function": "a"})[0]["outcome"] == "suppressed"
    assert eng.on_finding({"kind": "recompile_storm",
                           "function": "b"})[0]["outcome"] == "suppressed"
    da = eng.on_finding({"kind": "recompile_storm", "function": "a"})[0]
    db = eng.on_finding({"kind": "recompile_storm", "function": "b"})[0]
    assert da["outcome"] == "dry_run" and da["key"] == "a"
    assert db["outcome"] == "dry_run" and db["key"] == "b"


def test_observe_and_act_record_identical_decisions():
    """The acceptance contract: the same finding stream under observe
    and act yields the same decision stream — policy, action, gates,
    suppression reasons — differing ONLY in fired-vs-dry_run."""
    pol = [Policy(name="p", finding="k", action="retune",
                  cooldown_s=0.2, max_actions=1, window_s=3600)]
    streams = {}
    for mode in ("observe", "act"):
        eng = _engine([Policy(**vars(pol[0]))], mode=mode)
        out = []
        for _ in range(3):
            out += eng.on_finding({"kind": "k"})
        streams[mode] = out
    strip = ("ts", "outcome", "mode", "gate")
    norm = lambda ds: [{k: v for k, v in d.items() if k not in strip}
                       for d in ds]
    assert norm(streams["observe"]) == norm(streams["act"])
    assert [d["outcome"] for d in streams["observe"]] == \
        ["dry_run", "suppressed", "suppressed"]
    assert [d["outcome"] for d in streams["act"]] == \
        ["fired", "suppressed", "suppressed"]


def test_fired_action_dispatches():
    eng = _engine([Policy(name="p", finding="recompile_storm",
                          action="freeze_alert", cooldown_s=0.0)],
                  mode="act")
    d = eng.on_finding({"kind": "recompile_storm", "function": "hot_fn",
                        "compiles": 9})[0]
    assert d["outcome"] == "fired"
    deadline = time.time() + 5.0
    while time.time() < deadline and \
            "hot_fn" not in ap_actions.frozen_functions():
        time.sleep(0.02)
    assert "hot_fn" in ap_actions.frozen_functions()
    assert _counter(eng._reg, "hvd_autopilot_actions_total",
                    action="freeze_alert") == 1


# -- SLO gates ---------------------------------------------------------------

def _straggler_finding(excess=1.0):
    return {"kind": "persistent_straggler", "rank": 2,
            "win_step_time": 0.2 + excess, "fleet_mean": 0.2,
            "windows": 3}


def test_straggler_gate_fires_without_remesh_evidence():
    eng = _engine([Policy(name="p", finding="persistent_straggler",
                          action="drain_and_replace", cooldown_s=0.0)])
    d = eng.on_finding(_straggler_finding())[0]
    assert d["outcome"] == "dry_run"
    assert d["gate"]["remesh_p50_s"] is None
    assert d["gate"]["projected_loss_s"] > 0
    assert d["target_rank"] == 2


def test_straggler_gate_refuses_remesh_costlier_than_the_disease():
    from horovod_tpu.metrics import timeseries
    # measured history: re-meshes cost ~40s on this fleet
    for total in (35.0, 40.0, 45.0):
        timeseries.record_point({"remesh": {"rendezvous": total},
                                 "remesh_total_s": total,
                                 "complete": True})
    eng = _engine([Policy(name="p", finding="persistent_straggler",
                          action="drain_and_replace", cooldown_s=0.0,
                          horizon_steps=100)])
    # 0.1s excess * 100 steps = 10s projected loss < 40s p50: suppress
    d = eng.on_finding(_straggler_finding(excess=0.1))[0]
    assert d["outcome"] == "suppressed" and d["reason"] == "slo"
    assert d["gate"]["remesh_p50_s"] == pytest.approx(40.0)
    assert d["gate"]["projected_loss_s"] == pytest.approx(10.0)
    # 1s excess * 100 steps = 100s projected loss > 40s p50: worth it
    d = eng.on_finding(_straggler_finding(excess=1.0))[0]
    assert d["outcome"] == "dry_run"


def test_remesh_p50_deduplicates_ring_and_disk(tmp_path, monkeypatch):
    """Review hardening: a point still in the ring is ALSO on disk (the
    recorder writes both) — counting it twice weighted the p50 toward
    recent episodes and skewed the drain SLO gate."""
    from horovod_tpu.autopilot.engine import remesh_p50_s
    from horovod_tpu.metrics import timeseries
    monkeypatch.setenv("HVD_TPU_OBS_DIR", str(tmp_path))
    timeseries.reset()
    # two OLD episodes on disk only (rotated out of the ring)
    with open(tmp_path / "obs_rank0.jsonl", "w") as f:
        for ts in (1.0, 2.0):
            f.write(json.dumps({"ts": ts, "remesh_total_s": 10.0,
                                "remesh": {}, "complete": True}) + "\n")
    # two RECENT episodes through the recorder: ring AND disk
    for total in (100.0, 100.0):
        timeseries.record_point({"remesh": {}, "remesh_total_s": total,
                                 "complete": True})
    # median over the four DISTINCT episodes (10,10,100,100) = 55;
    # double-counting the recent pair would have said 100
    assert remesh_p50_s() == pytest.approx(55.0)


def test_straggler_gate_absolute_p50_cap():
    from horovod_tpu.metrics import timeseries
    timeseries.record_point({"remesh": {"rendezvous": 50.0},
                             "remesh_total_s": 50.0, "complete": True})
    eng = _engine([Policy(name="p", finding="persistent_straggler",
                          action="drain_and_replace", cooldown_s=0.0,
                          horizon_steps=10_000,
                          max_remesh_p50_s=30.0)])
    d = eng.on_finding(_straggler_finding(excess=1.0))[0]
    assert d["outcome"] == "suppressed" and d["reason"] == "slo"
    assert d["gate"]["max_remesh_p50_s"] == 30.0


def test_hbm_gate_needs_margin_evidence():
    eng = _engine([Policy(name="p", finding="hbm_growth",
                          action="commit_restart", cooldown_s=0.0,
                          max_margin_frac=0.1)])
    # no hbm gauges at all: growth alone is not "past the OOM margin"
    d = eng.on_finding({"kind": "hbm_growth", "growth_ratio": 1.4})[0]
    assert d["outcome"] == "suppressed" and d["reason"] == "slo"
    # comfortable margin: still suppressed, with the fraction recorded
    reg = eng._reg
    reg.gauge("hvd_hbm_oom_margin_bytes", agg="min").set(8e9)
    reg.gauge("hvd_hbm_limit_bytes", agg="min").set(16e9)
    d = eng.on_finding({"kind": "hbm_growth"})[0]
    assert d["outcome"] == "suppressed"
    assert d["gate"]["margin_frac"] == pytest.approx(0.5)
    # margin collapsed below the policy line: the planned restart fires
    reg.gauge("hvd_hbm_oom_margin_bytes", agg="min").set(1e9)
    d = eng.on_finding({"kind": "hbm_growth"})[0]
    assert d["outcome"] == "dry_run"
    assert d["gate"]["margin_frac"] == pytest.approx(1 / 16)


# -- the external report_finding() path --------------------------------------

def test_report_finding_path_matches_step_path(monkeypatch):
    """The recompile-storm policy depends on report_finding() findings
    flowing through matching/cooldown/budget IDENTICALLY to native
    ``_flag`` findings — drive the real anomaly engine both ways and
    assert the autopilot singleton saw both."""
    from horovod_tpu.metrics import anomaly
    doc = {"policies": [
        {"name": "ext", "finding": "recompile_storm",
         "action": "freeze_alert", "hysteresis": 2,
         "key_field": "function", "cooldown_s": 0.0},
        {"name": "native", "finding": "step_time_drift",
         "action": "retune", "cooldown_s": 3600}]}
    monkeypatch.setenv("HVD_TPU_AUTOPILOT_POLICY", json.dumps(doc))
    monkeypatch.setenv("HVD_TPU_AUTOPILOT", "observe")
    autopilot.reset()
    anomaly.reset()
    # external path: report_finding twice -> hysteresis then dry_run
    anomaly.report_finding("recompile_storm", function="f", compiles=5)
    anomaly.report_finding("recompile_storm", function="f", compiles=6)
    # native path: a step-time drift through observe_step's _flag
    eng = anomaly.default_engine()
    for i in range(30):
        eng.observe_step(i, 0.010)
    for i in range(30, 40):
        eng.observe_step(i, 0.300)
    decisions = autopilot.recent_decisions()
    by_policy = {}
    for d in decisions:
        by_policy.setdefault(d["policy"], []).append(d["outcome"])
    assert by_policy["ext"] == ["suppressed", "dry_run"]
    assert by_policy["native"] == ["dry_run"]
    # both paths hit the same counters on the default registry
    from horovod_tpu.metrics.registry import default_registry
    assert _counter(default_registry(), "hvd_autopilot_decisions_total",
                    policy="ext", outcome="dry_run") >= 1
    assert _counter(default_registry(), "hvd_autopilot_decisions_total",
                    policy="native", outcome="dry_run") >= 1


def test_world_changed_finding_reported_on_resize():
    from horovod_tpu.elastic import remesh
    from horovod_tpu.metrics import anomaly
    remesh.reset()
    remesh.begin("internal_error", old_size=4)
    remesh.mark_recovered(new_size=3, generation=7)
    found = [f for f in anomaly.recent_findings()
             if f["kind"] == "world_changed"]
    assert found and found[0]["old_size"] == 4 \
        and found[0]["new_size"] == 3
    # the default topology-retune policy saw it (observe default)
    assert any(d["policy"] == "topology-retune"
               for d in autopilot.recent_decisions())
    remesh.reset()
    # same-size recovery: NOT a topology change
    anomaly.reset()
    remesh.begin("internal_error", old_size=3)
    remesh.mark_recovered(new_size=3, generation=8)
    assert not [f for f in anomaly.recent_findings()
                if f["kind"] == "world_changed"]
    remesh.reset()


# -- local remediations ------------------------------------------------------

def test_retune_invalidates_plan_cache_and_runs_hooks(tmp_path,
                                                      monkeypatch):
    cache = tmp_path / "plans"
    cache.mkdir()
    (cache / "plan_abc.json").write_text("{}")
    (cache / "plan_def.json").write_text("{}")
    (cache / "unrelated.txt").write_text("keep me")
    monkeypatch.setenv("HVD_TPU_AUTOTUNE_CACHE_DIR", str(cache))
    from horovod_tpu.common.config import reset_config
    reset_config()
    ran = []
    ap_actions.register_retune_hook(lambda: ran.append(1))
    removed = ap_actions.retune()
    assert removed == 2
    assert (cache / "unrelated.txt").exists()
    assert ran == [1]
    reset_config()


def test_invalidate_plan_cache_off_is_zero(monkeypatch):
    monkeypatch.delenv("HVD_TPU_AUTOTUNE_CACHE_DIR", raising=False)
    from horovod_tpu.common.config import reset_config
    reset_config()
    from horovod_tpu.train.autotune import invalidate_plan_cache
    assert invalidate_plan_cache() == 0
    reset_config()


# -- the audit trail: JSONL + CLI + autopsy ----------------------------------

def test_actions_jsonl_and_history_cli(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_TPU_OBS_DIR", str(tmp_path))
    eng = _engine([Policy(name="audit-me", finding="k",
                          action="freeze_alert", cooldown_s=0.0)])
    eng.on_finding({"kind": "k", "function": "f"})
    eng.on_finding({"kind": "k", "function": "f"})
    path = tmp_path / "actions_rank0.jsonl"
    assert path.exists()
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(rows) == 2 and rows[0]["policy"] == "audit-me"
    # the CLI renders the decision table from the same files
    from horovod_tpu.metrics.__main__ import main as metrics_main
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = metrics_main(["history", "--dir", str(tmp_path),
                           "--actions"])
    assert rc == 0
    out = buf.getvalue()
    assert "audit-me" in out and "dry_run" in out
    assert "2 decision(s)" in out
    # --json emits raw rows
    buf = io.StringIO()
    with redirect_stdout(buf):
        assert metrics_main(["history", "--dir", str(tmp_path),
                             "--actions", "--json", "--last", "1"]) == 0
    assert json.loads(buf.getvalue())["policy"] == "audit-me"
    # an empty dir reports cleanly
    empty = tmp_path / "empty"
    empty.mkdir()
    assert metrics_main(["history", "--dir", str(empty),
                         "--actions"]) == 1


def test_suppressed_decision_with_jsonl_log_does_not_deadlock(
        tmp_path, monkeypatch):
    """Regression: the suppressed-decision paths used to call the
    recorder while still holding the engine's (non-reentrant) gate
    lock — with ``HVD_TPU_OBS_DIR`` set the JSONL writer re-acquired
    it and the process self-deadlocked on its second finding."""
    monkeypatch.setenv("HVD_TPU_OBS_DIR", str(tmp_path))
    eng = _engine([Policy(name="p", finding="k", action="freeze_alert",
                          cooldown_s=3600)])
    assert eng.on_finding({"kind": "k"})[0]["outcome"] == "dry_run"
    d = eng.on_finding({"kind": "k"})[0]  # used to hang right here
    assert d["outcome"] == "suppressed" and d["reason"] == "cooldown"
    rows = [json.loads(l) for l in
            (tmp_path / "actions_rank0.jsonl").read_text().splitlines()]
    assert [r["outcome"] for r in rows] == ["dry_run", "suppressed"]


def test_top_renders_autopilot_line():
    from horovod_tpu.metrics.__main__ import render_top
    series = {
        "hvd_autopilot_mode": 2.0,
        'hvd_autopilot_decisions_total{outcome="fired",policy="sd"}': 1.0,
        'hvd_autopilot_decisions_total{outcome="suppressed",policy="sd"}':
            3.0,
    }
    frame = render_top(series, "test")
    line = next(l for l in frame.splitlines() if "AUTOPILOT" in l)
    assert "[act]" in line
    assert "sd fired×1" in line and "sd suppressed×3" in line


def test_autopsy_summary_embeds_actions(tmp_path, monkeypatch):
    monkeypatch.setenv("HVD_TPU_AUTOPILOT", "observe")
    monkeypatch.setenv("HVD_TPU_AUTOPILOT_POLICY", json.dumps(
        {"policies": [{"name": "aut", "finding": "k",
                       "action": "freeze_alert"}]}))
    autopilot.reset()
    # go through the singleton: the autopsy reads recent_decisions()
    autopilot.ensure_engine()
    autopilot.on_finding({"kind": "k"})
    from horovod_tpu.diagnostics.autopsy import write_autopsy
    bundle = write_autopsy(str(tmp_path / "b"), reason="test",
                           fetch_peers=False)
    summary = json.load(open(os.path.join(
        bundle, [f for f in os.listdir(bundle)
                 if f.startswith("summary_rank")][0])))
    assert summary["actions"], summary
    assert summary["actions"][-1]["policy"] == "aut"


# -- driver-side action validation -------------------------------------------

class _AliveThread:
    def is_alive(self):
        return True


class _Slot:
    def __init__(self, hostname):
        self.hostname = hostname


def _fake_gen_runtime():
    from horovod_tpu.runner.elastic.driver import _GenRuntime
    g = _GenRuntime([], 0, "127.0.0.1", 0)
    for r in (0, 1, 2):
        key = (0, r)
        g.essential_keys.append(key)
        g.current_rank[key] = r
        g.slot_by_key[key] = _Slot("localhost")
        g.threads[key] = _AliveThread()
    return g


def test_driver_scans_and_validates_action_requests():
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import HostInfo
    driver = ElasticDriver(FixedHosts([HostInfo("localhost", 3)]),
                           ["true"], min_np=1)
    try:
        g = _fake_gen_runtime()
        put = driver._kv.put
        put("action", "0-1", json.dumps(
            {"action": "drain", "rank": 2, "generation": 0,
             "policy": "straggler-drain"}).encode())
        put("action", "0-2", json.dumps(
            {"action": "restart", "rank": 1, "generation": 0,
             "policy": "hbm-planned-restart"}).encode())
        put("action", "0-3", b"not json")                # burned
        put("action", "0-4", json.dumps(                  # unknown kind
            {"action": "explode", "rank": 0,
             "generation": 0}).encode())
        put("action", "0-5", json.dumps(                  # stale gen
            {"action": "drain", "rank": 0,
             "generation": 99}).encode())
        put("action", "0-6", json.dumps(                  # unknown rank
            {"action": "drain", "rank": 7,
             "generation": 0}).encode())
        groups = driver._scan_action_requests(g)
        drains, dmeta, dtokens = groups["drain"]
        restarts, rmeta, rtokens = groups["restart"]
        assert {g.current_rank[k] for k in drains} == {2}
        assert dmeta[0]["policy"] == "straggler-drain"
        assert dmeta[0]["source"] == "autopilot"
        assert {g.current_rank[k] for k in restarts} == {1}
        # malformed/unknown/stale-rank burned; stale GENERATION is not
        # (the numbering window may catch up) — 3 burned tokens
        burned = {t[1] for t in g.handled_tokens}
        assert burned == {"0-3", "0-4", "0-6"}
        # without notify registrations nothing can be planned: the
        # request defers untouched (no tokens consumed, no reservation)
        assert not driver._poll_action_requests(g)
        assert "0-1" not in {t[1] for t in g.handled_tokens}
    finally:
        driver._kv.stop()


def test_action_publish_requires_driver_kv(monkeypatch):
    monkeypatch.delenv("HVD_ELASTIC_KV", raising=False)
    pol = Policy(name="p", finding="persistent_straggler",
                 action="drain_and_replace")
    ok = ap_actions._request_driver_action("drain", 2, pol,
                                           {"finding": "k"})
    assert ok is False
    from horovod_tpu.diagnostics.flight_recorder import recorder
    assert any(e["kind"] == "autopilot_action_unroutable"
               for e in recorder().events())


def test_action_publish_lands_in_kv_scope(monkeypatch):
    from horovod_tpu.runner.http_kv import KVStoreServer
    from horovod_tpu.runner import kv_relay
    srv = KVStoreServer()
    srv.start()
    try:
        monkeypatch.setenv("HVD_ELASTIC_KV", f"127.0.0.1:{srv.port}")
        monkeypatch.setenv("HVD_ELASTIC_GENERATION", "3")
        kv_relay.reset()
        pol = Policy(name="p", finding="persistent_straggler",
                     action="drain_and_replace")
        assert ap_actions._request_driver_action(
            "drain", 2, pol, {"finding": "persistent_straggler"})
        entries = srv.scope("action")
        assert len(entries) == 1
        req = json.loads(next(iter(entries.values())))
        assert req["action"] == "drain" and req["rank"] == 2
        assert req["generation"] == 3 and req["source"] == "autopilot"
    finally:
        srv.stop()
        kv_relay.reset()


# -- end-to-end acceptance (slow): chaos straggler -> autonomous drain -------

def _free_port_base(n=3):
    """Base port with base+1..base+n-1 also free (worker i binds
    base + local_rank)."""
    for _ in range(50):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
        s.close()
        try:
            probes = []
            for i in range(1, n):
                p = socket.socket()
                p.bind(("127.0.0.1", base + i))
                probes.append(p)
            for p in probes:
                p.close()
            return base
        except OSError:
            continue
    raise RuntimeError("no free port window")


def _straggler_worker_prog(log, flights, metrics_out, finish_step,
                           min_generation):
    """Worker for the autopilot acceptance: an UNSYNCHRONIZED
    telemetry loop (commit-only coordination — per-step collectives
    would equalize step times across ranks and hide the straggler from
    the fleet's win_step_time attribution), with the chaos ``step``
    stall keyed on the SYNCED state.step so a drained worker's
    replacement (which resumes past the window) does not re-straggle."""
    return textwrap.dedent(f"""
        import os, sys, time
        sys.path.insert(0, {REPO!r})
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu import chaos, elastic
        from horovod_tpu.diagnostics.flight_recorder import recorder
        from horovod_tpu.train.callbacks import StepTimer

        orig_rank = int(os.environ["HOROVOD_RANK"])
        hvd.init()
        with open({str(log)!r}, "a") as f:
            f.write(f"BOOT rank={{orig_rank}} pid={{os.getpid()}}\\n")

        state = elastic.ObjectState(name="autorun", step=0, durable=True)

        @elastic.run
        def train(state):
            timer = StepTimer(unit="examples")
            while True:
                timer.start_step()
                chaos.step_tick(state.step)   # the straggler stall
                time.sleep(0.05)
                timer.end_step(32)
                state.step += 1
                state.commit()
                gen = int(os.environ.get("HVD_ELASTIC_GENERATION", "0"))
                if state.step >= {finish_step} and hvd.size() == 3 \\
                        and gen >= {min_generation}:
                    return True

        train(state)
        state.flush()
        if hvd.rank() == 0:
            from horovod_tpu.metrics.registry import (default_registry,
                                                      render_prometheus)
            with open({str(metrics_out)!r}, "w") as f:
                f.write(render_prometheus(default_registry().snapshot()))
        recorder().dump_to(os.path.join(
            {str(flights)!r},
            f"flight_rank{{hvd.rank()}}_pid{{os.getpid()}}.json"))
        with open({str(log)!r}, "a") as f:
            f.write(f"DONE rank={{hvd.rank()}} pid={{os.getpid()}} "
                    f"size={{hvd.size()}} step={{state.step}}\\n")
        hvd.shutdown()
    """)


def _run_straggler_scenario(tmp_path, monkeypatch, name, mode,
                            min_generation):
    from horovod_tpu.runner.elastic.discovery import FixedHosts
    from horovod_tpu.runner.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import HostInfo
    base = tmp_path / name
    base.mkdir()
    log = base / "events.log"
    flights = base / "flights"
    flights.mkdir()
    obs = base / "obs"
    metrics_out = base / "metrics_rank0.prom"
    plan_file = base / "plan.json"
    # rank 2 straggles: every step in [1, 6) stalls 1.2s INSIDE the
    # timed window, against ~0.05s peers — an unambiguous persistent
    # straggler for the fleet detector within two 0.4s windows
    plan_file.write_text(json.dumps({"faults": [
        {"seam": "step", "kind": "stall", "rank": 2,
         "start": 1, "stop": 6, "stall_s": 1.2}]}))
    prog = base / "train.py"
    # 40 fast (~0.1s) steps keep the healthy ranks running well past
    # the straggler's detection window before they may finish
    prog.write_text(_straggler_worker_prog(
        log, flights, metrics_out, finish_step=40,
        min_generation=min_generation))
    env = dict(os.environ)
    env.update({
        "HVD_TPU_FAULT_PLAN": str(plan_file),
        "HVD_TPU_AUTOPILOT": mode,
        "HVD_TPU_OBS_DIR": str(obs),
        "HVD_TPU_METRICS_PORT": str(_free_port_base(3)),
        "HVD_TPU_FLEET_PUSH_SECONDS": "0.4",
        "HVD_TPU_ANOMALY_STRAGGLER_WINDOWS": "2",
        "HVD_TPU_CHECKPOINT_DIR": str(base / "ckpt"),
        "HVD_TPU_CHECKPOINT_COMMIT_TIMEOUT_S": "5",
        "HVD_TPU_AUTOPSY_DIR": str(base / "autopsy"),
        "HVD_TPU_METADATA_ENDPOINT": "http://127.0.0.1:1",
        "HVD_TPU_PREEMPTION_POLL_S": "0.5",
        "HVD_TPU_TRANSPORT_TIMEOUT_S": "20",
    })
    env.pop("HVD_TPU_AUTOPILOT_POLICY", None)  # the shipped policy set
    monkeypatch.setenv("HVD_TPU_DRAIN_COOLDOWN_S", "2")
    driver = ElasticDriver(
        FixedHosts([HostInfo("localhost", 3)]),
        [sys.executable, str(prog)],
        min_np=2, max_np=3, target_np=3, reset_limit=4,
        ckpt_dir=str(base), env=env)
    rc = driver.run()
    lines = log.read_text().strip().splitlines() if log.exists() else []
    decisions = []
    for f in sorted(obs.glob("actions_rank*.jsonl")) \
            if obs.exists() else []:
        decisions += [json.loads(l)
                      for l in f.read_text().splitlines()]
    return rc, lines, decisions, metrics_out, flights, driver


@pytest.mark.slow
def test_autopilot_straggler_drain_act(tmp_path, monkeypatch):
    """The ISSUE 12 acceptance, act half: a chaos-injected persistent
    straggler on a 3-process elastic job is detected by the fleet
    anomaly engine, SLO-gated, and drain-replaced to a healthy
    full-size world with ZERO human input — and the decision is
    visible on /metrics, in the flight ring, and in
    ``history --actions``."""
    from horovod_tpu.core import core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")
    rc, lines, decisions, metrics_out, flights, driver = \
        _run_straggler_scenario(tmp_path, monkeypatch, "act", "act",
                                min_generation=2)
    assert rc == 0, lines
    boots = [l for l in lines if l.startswith("BOOT")]
    dones = [l for l in lines if l.startswith("DONE")]
    # 3 originals + exactly one replacement after the drain cooldown
    assert len(boots) == 4, lines
    assert len(dones) == 3, lines
    for d in dones:
        assert "size=3" in d, lines  # healed back to full size
    # the straggler's host was never treated as bad
    assert not driver._hosts.is_blacklisted("localhost")
    # driver-side evidence: the action was handled as a planned drain
    from horovod_tpu.diagnostics.flight_recorder import recorder
    handled = [e for e in recorder().events()
               if e["kind"] == "autopilot_action_handled"]
    assert any(e.get("drained_ranks") == [2]
               and e.get("notices", [{}])[0].get("source") == "autopilot"
               and e.get("notices", [{}])[0].get("policy")
               == "straggler-drain" for e in handled), handled
    # the decision audit trail: fired, with the gate inputs recorded
    fired = [d for d in decisions if d["policy"] == "straggler-drain"]
    assert fired and fired[0]["outcome"] == "fired", decisions
    assert fired[0]["action"] == "drain_and_replace"
    assert fired[0]["target_rank"] == 2
    assert "remesh_p50_s" in fired[0]["gate"]
    # /metrics carries the decision counters and the act mode
    prom = metrics_out.read_text()
    assert 'hvd_autopilot_decisions_total{outcome="fired",' \
           'policy="straggler-drain"} 1' in prom, prom
    assert 'hvd_autopilot_actions_total{action="drain_and_replace"} 1' \
        in prom, prom
    assert "hvd_autopilot_mode 2" in prom
    # the worker flight ring carries the decision event
    flight_kinds = set()
    for f in flights.glob("*.json"):
        for e in json.load(open(f)).get("events", []):
            flight_kinds.add(e["kind"])
    assert "autopilot_decision" in flight_kinds, sorted(flight_kinds)
    # the survivors measured the planned re-mesh (drain-stamped world)
    remesh = []
    anomaly_evs = []
    phase_evs = []
    for f in flights.glob("*.json"):
        for e in json.load(open(f)).get("events", []):
            if e["kind"] == "remesh_complete":
                remesh.append(e)
            elif e["kind"] == "remesh_phase":
                phase_evs.append(e)
            elif e["kind"] == "anomaly" \
                    and e.get("detector") == "persistent_straggler":
                anomaly_evs.append(e)
    assert any(e.get("trigger") == "preemption_drain" for e in remesh), \
        remesh
    # ISSUE 15 acceptance (b): ONE trace id links the whole causal
    # chain — the persistent_straggler finding, the SLO-gated
    # decision, the driver's autopilot_action_handled, and every phase
    # of the resulting re-mesh episode
    tr = fired[0].get("trace")
    assert tr and len(tr) == 32, fired[0]
    assert fired[0].get("parent"), fired[0]  # childs the finding span
    assert any(e.get("trace") == tr for e in anomaly_evs), \
        (tr, anomaly_evs)
    assert any(e.get("trace") == tr for e in handled), (tr, handled)
    drain_episode = [e for e in remesh if e.get("trace") == tr]
    assert drain_episode \
        and drain_episode[0]["trigger"] == "preemption_drain", \
        (tr, remesh)
    traced_phases = {e.get("phase") for e in phase_evs
                     if e.get("trace") == tr}
    assert {"failure_detect", "restore", "first_step"} <= traced_phases, \
        (tr, traced_phases)
    # and the CLI renders the trail
    import subprocess
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.metrics", "history",
         "--actions", "--dir", str(tmp_path / "act" / "obs")],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stderr
    assert "straggler-drain" in out.stdout and "fired" in out.stdout
    # the merged timeline joins worker flight dumps + the actions/
    # re-mesh history on one clock, and `trace <id>` prints the chain
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.diagnostics", "trace", tr,
         "--dir", str(flights),
         "--obs-dir", str(tmp_path / "act" / "obs")],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stderr + out.stdout
    assert "anomaly" in out.stdout or "persistent_straggler" \
        in out.stdout, out.stdout
    assert "fired" in out.stdout, out.stdout
    assert "remesh" in out.stdout, out.stdout
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.diagnostics", "timeline",
         "--dir", str(flights),
         "--obs-dir", str(tmp_path / "act" / "obs"),
         "-o", str(tmp_path / "act" / "merged_timeline.json")],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 0, out.stderr
    merged = json.load(open(tmp_path / "act" / "merged_timeline.json"))
    traced = [e for e in merged["traceEvents"]
              if (e.get("args") or {}).get("trace") == tr]
    assert len({e["pid"] for e in traced}) >= 2, traced


@pytest.mark.slow
def test_autopilot_straggler_observe_records_without_acting(
        tmp_path, monkeypatch):
    """The observe half: the IDENTICAL fault plan records the same
    decision — same policy, action, target, gate inputs — and takes no
    action: no re-mesh, no replacement, the job finishes with its
    original three processes."""
    from horovod_tpu.core import core_available
    if not core_available():
        pytest.skip("libhvdcore.so not built")
    rc, lines, decisions, metrics_out, flights, _driver = \
        _run_straggler_scenario(tmp_path, monkeypatch, "observe",
                                "observe", min_generation=0)
    assert rc == 0, lines
    boots = [l for l in lines if l.startswith("BOOT")]
    dones = [l for l in lines if l.startswith("DONE")]
    assert len(boots) == 3, lines   # nobody was replaced
    assert len(dones) == 3, lines
    # the identical decision, recorded as a dry run
    dry = [d for d in decisions if d["policy"] == "straggler-drain"]
    assert dry and dry[0]["outcome"] == "dry_run", decisions
    assert dry[0]["action"] == "drain_and_replace"
    assert dry[0]["target_rank"] == 2
    assert "remesh_p50_s" in dry[0]["gate"]
    # and nothing acted: no re-mesh episode anywhere
    for f in flights.glob("*.json"):
        events = json.load(open(f)).get("events", [])
        assert not [e for e in events if e["kind"] == "remesh_complete"]
    prom = metrics_out.read_text()
    assert 'hvd_autopilot_decisions_total{outcome="dry_run",' \
           'policy="straggler-drain"} 1' in prom, prom
    assert "hvd_autopilot_mode 1" in prom
    assert "hvd_autopilot_actions_total" not in prom
