"""Headline benchmark: ResNet-50 synthetic training throughput per chip.

Matches the reference's canonical harness (synthetic-data img/sec,
``examples/pytorch/pytorch_synthetic_benchmark.py`` /
``docs/benchmarks.rst:67-80``). Baseline for ``vs_baseline``: the reference's
published 16-GPU ResNet-101 number — 1656.82 img/s total = 103.55
img/s/GPU (``docs/benchmarks.rst:32-43``, 4×4 Pascal P100, batch 64) — the
only absolute throughput the reference publishes.

Hardened for the driver contract:
- the measurement runs in a CHILD process, so every retry gets a fresh JAX
  (a failed backend init is cached for the life of a process);
- bounded retry with backoff on TPU-backend init failure;
- on persistent failure the parent prints ONE diagnostic JSON line (rc 0)
  instead of a traceback, so the artifact always parses;
- reports ``mfu`` computed from compiled-HLO FLOPs (fallback: analytic
  ResNet-50 estimate) against the chip's peak bf16 FLOPs.

stdout carries exactly one JSON line:
{"metric", "value", "unit", "vs_baseline", "mfu", ...}.
"""

import json
import os
import subprocess
import sys
import time

REFERENCE_IMG_PER_SEC_PER_DEVICE = 1656.82 / 16  # docs/benchmarks.rst:32-43

# Peak dense bf16 FLOPs per chip by device-kind substring (public specs).
PEAK_BF16_FLOPS = (
    ("v5 lite", 197e12), ("v5litepod", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v6", 918e12), ("v4", 275e12), ("v3", 123e12),
    ("v2", 45e12),
)

# ResNet-50 @224: ~4.09e9 MACs forward => 2x FLOPs, training ~3x forward.
ANALYTIC_TRAIN_FLOPS_PER_IMG = 3 * 2 * 4.09e9

ATTEMPTS = 3
BACKOFFS_S = (10, 30)
ATTEMPT_DEADLINE_S = 1500  # generous: a good run is ~2-3 min incl. compile


def _peak_flops(device_kind: str):
    kind = device_kind.lower()
    for sub, peak in PEAK_BF16_FLOPS:
        if sub in kind:
            return peak
    return None


def _child() -> None:
    """Run the actual measurement; print the result JSON line to stdout."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models.resnet import (ResNet50, create_resnet_state,
                                           make_resnet_train_step,
                                           batch_sharding)

    def log(msg: str) -> None:
        print(f"[bench] {msg}", file=sys.stderr, flush=True)

    log(f"devices: {jax.devices()}")
    hvd.init()
    mesh = hvd.build_mesh(dp=-1)
    n_chips = int(np.prod(list(mesh.shape.values())))

    batch_per_chip = 256
    B = batch_per_chip * n_chips
    # MLPerf-style space-to-depth stem by default: the 7x7/s2 conv over
    # C=3 wastes 4x of the MXU's input-channel tiling (docs/PERF.md);
    # HVD_BENCH_STEM=conv selects the textbook stem for comparison.
    stem = os.environ.get("HVD_BENCH_STEM", "s2d")
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16, stem=stem)
    params, batch_stats = create_resnet_state(
        model, jax.random.PRNGKey(0), image_size=224, mesh=mesh)
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = jax.jit(tx.init)(params)
    step = make_resnet_train_step(model, tx, mesh)

    rng = np.random.RandomState(0)
    images = jax.device_put(
        jnp.asarray(rng.rand(B, 224, 224, 3), jnp.bfloat16),
        batch_sharding(mesh))
    labels = jax.device_put(
        jnp.asarray(rng.randint(0, 1000, (B,)), jnp.int32),
        batch_sharding(mesh))

    # warmup (compile + stabilize), then drain the dispatch queue with a
    # host readback — jax.block_until_ready is unreliable on the axon
    # platform (returns before execution completes), so timing brackets use
    # float() readbacks.
    log("compiling + warmup...")
    for _ in range(3):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
    float(loss)
    log("warmup done; timing...")

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
    float(loss)  # forces completion of the whole chain
    dt = time.perf_counter() - t0

    img_per_sec = B * iters / dt
    per_chip = img_per_sec / n_chips

    # FLOPs PER DEVICE per step: cost_analysis() describes the post-SPMD-
    # partition per-device executable; the analytic fallback divides the
    # global-batch estimate by n_chips so both feed the same formula.
    flops_per_device = None
    flops_src = "hlo"
    try:
        cost = step.lower(params, batch_stats, opt_state, images,
                          labels).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops_per_device = float(cost.get("flops", 0.0)) or None
    except Exception as e:
        log(f"cost_analysis unavailable ({e!r}); using analytic FLOPs")
    if not flops_per_device:
        flops_per_device = ANALYTIC_TRAIN_FLOPS_PER_IMG * B / n_chips
        flops_src = "analytic"

    peak = _peak_flops(jax.devices()[0].device_kind)
    mfu = None
    if peak:
        mfu = round(flops_per_device * iters / dt / peak, 4)

    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(per_chip / REFERENCE_IMG_PER_SEC_PER_DEVICE, 3),
        "mfu": mfu,
        "flops_per_device_per_step": flops_per_device,
        "flops_source": flops_src,
        "n_chips": n_chips,
        "device_kind": jax.devices()[0].device_kind,
        "batch_per_chip": batch_per_chip,
        "stem": stem,
    }), flush=True)


def _run_attempt():
    """Run one child attempt; return (result_line | None, error_tail)."""
    proc = subprocess.Popen(
        [sys.executable, "-u", os.path.abspath(__file__), "--child"],
        stdout=subprocess.PIPE, stderr=sys.stderr, text=True,
        cwd=os.path.dirname(os.path.abspath(__file__)))
    try:
        out, _ = proc.communicate(timeout=ATTEMPT_DEADLINE_S)
    except subprocess.TimeoutExpired:
        # SIGTERM lets the PJRT client tear down its chip claim; never
        # SIGKILL a process mid-claim (it wedges the relay lease).
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            pass  # abandon rather than SIGKILL
        return None, f"attempt exceeded {ATTEMPT_DEADLINE_S}s deadline"
    for line in reversed((out or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
            if isinstance(parsed, dict) and "metric" in parsed:
                return line, None
        except ValueError:
            continue
    tail = (out or "").strip().splitlines()[-5:]
    return None, f"child rc={proc.returncode}: " + " | ".join(tail)[-600:]


def main() -> None:
    errors = []
    for i in range(ATTEMPTS):
        line, err = _run_attempt()
        if line is not None:
            print(line, flush=True)
            return
        errors.append(f"attempt {i + 1}: {err}")
        print(f"[bench] {errors[-1]}", file=sys.stderr, flush=True)
        if i < ATTEMPTS - 1:
            time.sleep(BACKOFFS_S[min(i, len(BACKOFFS_S) - 1)])
    # Persistent failure: still emit one parseable JSON line, rc 0.
    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": None,
        "unit": "img/s/chip",
        "vs_baseline": None,
        "mfu": None,
        "error": "; ".join(errors)[-800:],
        "attempts": ATTEMPTS,
    }), flush=True)


if __name__ == "__main__":
    if "--child" in sys.argv:
        _child()
    else:
        main()
