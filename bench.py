"""Headline benchmark: ResNet-50 synthetic training throughput per chip.

Matches the reference's canonical harness (synthetic-data img/sec,
``examples/pytorch/pytorch_synthetic_benchmark.py`` /
``docs/benchmarks.rst:67-80``). Baseline for ``vs_baseline``: the reference's
published 16-GPU ResNet-101 number — 1656.82 img/s total = 103.55
img/s/GPU (``docs/benchmarks.rst:32-43``, 4×4 Pascal P100, batch 64) — the
only absolute throughput the reference publishes.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np

REFERENCE_IMG_PER_SEC_PER_DEVICE = 1656.82 / 16  # docs/benchmarks.rst:32-43


def main() -> None:
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models.resnet import (ResNet50, create_resnet_state,
                                           make_resnet_train_step,
                                           batch_sharding)

    hvd.init()
    mesh = hvd.build_mesh(dp=-1)
    n_chips = int(np.prod(list(mesh.shape.values())))

    batch_per_chip = 256
    B = batch_per_chip * n_chips
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    params, batch_stats = create_resnet_state(
        model, jax.random.PRNGKey(0), image_size=224, mesh=mesh)
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = jax.jit(tx.init)(params)
    step = make_resnet_train_step(model, tx, mesh)

    rng = np.random.RandomState(0)
    images = jax.device_put(
        jnp.asarray(rng.rand(B, 224, 224, 3), jnp.bfloat16),
        batch_sharding(mesh))
    labels = jax.device_put(
        jnp.asarray(rng.randint(0, 1000, (B,)), jnp.int32),
        batch_sharding(mesh))

    # warmup (compile + stabilize), then drain the dispatch queue with a
    # host readback — jax.block_until_ready is unreliable on the axon
    # platform (returns before execution completes), so timing brackets use
    # float() readbacks.
    for _ in range(3):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
    float(loss)

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels)
    float(loss)  # forces completion of the whole chain
    dt = time.perf_counter() - t0

    img_per_sec = B * iters / dt
    per_chip = img_per_sec / n_chips
    print(json.dumps({
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(per_chip, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(per_chip / REFERENCE_IMG_PER_SEC_PER_DEVICE, 3),
    }))


if __name__ == "__main__":
    main()
