"""Headline benchmark: ResNet-50 synthetic training throughput per chip.

Matches the reference's canonical harness (synthetic-data img/sec,
``examples/pytorch/pytorch_synthetic_benchmark.py`` /
``docs/benchmarks.rst:67-80``). Baseline for ``vs_baseline``: the reference's
published 16-GPU ResNet-101 number — 1656.82 img/s total = 103.55
img/s/GPU (``docs/benchmarks.rst:32-43``, 4×4 Pascal P100, batch 64) — the
only absolute throughput the reference publishes.

``HVD_BENCH_MODEL`` selects the model: ``resnet50`` (default) /
``resnet50_bare`` (the SAME model in plain flax+optax with no
horovod_tpu anywhere — the framework-overhead control) /
``resnet101`` / ``vgg16`` / ``inception3`` / ``bert`` (BERT-Large
pretraining, the BASELINE north-star secondary model) / ``gpt`` (decoder
LM on the flagship transformer; shape via ``HVD_BENCH_GPT_{LAYERS,DMODEL,
HEADS,DFF}``). ``HVD_BENCH_BATCH`` / ``HVD_BENCH_SEQ`` / ``HVD_BENCH_STEM``
tune shapes. ``--compression int8|fp8|onebit|fp16|bf16`` (or
``HVD_BENCH_COMPRESSION``) wraps the optimizer in error-feedback
gradient compression so the codec's in-graph cost lands in the measured
step (docs/PERF.md "Gradient compression"). ``--autotune`` (or
``HVD_BENCH_AUTOTUNE=1``) warm-starts the communication knobs from the
persistent mesh-autotune plan cache (docs/PERF.md "Autotuning").
See docs/PERF.md for
recorded numbers.

Hardened for the driver contract:
- the measurement runs in a CHILD process, so every retry gets a fresh JAX
  (a failed backend init is cached for the life of a process);
- a PERSISTENT compilation cache (repo-local ``.jax_cache``) so retries
  and successive driver rounds compile warm instead of paying the
  multi-minute cold compile that blew round 3's deadline;
- a PROVISIONAL result (measured warmup-window throughput,
  ``"provisional": true``) is emitted before the patient timing window
  and salvaged by the streaming parent, so even a deadline-killed run
  carries a real measured number;
- hard TOTAL wall-clock budget (``HVD_BENCH_TOTAL_BUDGET_S``, default
  1200 s): one patient attempt sized to the remaining budget, fast
  retries only if budget remains, fallback JSON emitted BEFORE the cap;
- on persistent failure the parent prints ONE diagnostic JSON line (rc 0)
  instead of a traceback, so the artifact always parses;
- reports ``mfu`` computed from compiled-HLO FLOPs (fallback: analytic
  estimate) against the chip's peak bf16 FLOPs.

stdout carries exactly one JSON line:
{"metric", "value", "unit", "vs_baseline", "mfu", ...}.
"""

import json
import os
import subprocess
import sys
import threading
import time

REFERENCE_IMG_PER_SEC_PER_DEVICE = 1656.82 / 16  # docs/benchmarks.rst:32-43

# fwd GMACs per image (224 input; inception3 at its native 299);
# FLOPs = 2x MACs, training ~3x forward.
FWD_MACS_PER_IMG = {"resnet50": 4.09e9, "resnet101": 7.6e9,
                    "vgg16": 15.47e9, "inception3": 5.7e9}

# Total wall-clock budget for the WHOLE bench run (all attempts + the
# fallback emission). A good run is ~2-3 min incl. compile; the budget
# exists so the driver's own deadline never kills us mid-attempt with
# nothing on stdout (round-2 failure mode: escalating per-attempt
# deadlines of 1500/2400/3600s out-waited the driver → rc=124,
# parsed=null). One patient attempt inside a hard cap, fallback JSON
# emitted BEFORE the cap, is strictly better than three attempts that
# can never all finish.
TOTAL_BUDGET_S = float(os.environ.get("HVD_BENCH_TOTAL_BUDGET_S", "1200"))
# Reserved at the end of the budget for writing the fallback JSON and
# reaping a wedged child.
FALLBACK_RESERVE_S = 100.0
BACKOFF_S = 10
# Secondary bound: a fast-failing attempt (backend down) must not spin
# through dozens of retries even though budget remains.
MAX_ATTEMPTS = 5


def _git_commit() -> str:
    """Short commit hash for result provenance (empty off-git)."""
    try:
        return subprocess.run(
            ["git", "-C", os.path.dirname(os.path.abspath(__file__)),
             "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=5).stdout.strip()
    except Exception:
        return ""


def _clean_exit(code: int = 0) -> None:
    """Finish the child with grace-then-escalate semantics (the self-exit
    analog of TERM→wait→KILL, under an explicit deadline instead of a
    load-sensitive fixed wait).  Everything that matters — the result
    JSON on stdout, the phase file — is flushed HERE, so whatever
    happens afterwards is teardown politeness, not data.

    Two teardown failure modes under load used to flip a finished run
    into a dirty one (the child_exits_cleanly flake): XLA:CPU teardown
    CRASHES (glibc "double free" aborts — synchronous C aborts that no
    Python-level signal handler can intercept) or WEDGES.  Off-TPU there
    is no chip claim to release, so teardown buys nothing: hard-exit
    immediately after the flush.  On TPU a dirty exit wedges the relay
    lease for the NEXT run, so tear down politely — but under
    ``HVD_BENCH_EXIT_GRACE_S`` (default 30s; 0 = no escalation), after
    which a daemon timer hard-exits with the SAME status rather than
    letting the parent's kill path classify a clean run as dirty.
    (Limitation: a daemon Timer can fire during the atexit phase — where
    the observed PJRT/relay wedges live — but not once interpreter
    finalization has frozen daemon threads; a wedge that deep still
    falls to the parent's TERM→wait→KILL.)"""
    _flush_phase_file()
    try:
        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:
        pass
    platform = ""
    try:
        import jax
        platform = jax.default_backend()
    except Exception:
        pass
    if platform != "tpu":
        os._exit(code)
    try:
        grace = float(os.environ.get("HVD_BENCH_EXIT_GRACE_S", "30"))
    except ValueError:
        grace = 30.0
    if grace > 0:
        def _escalate():
            _log(f"clean exit did not complete within {grace:.0f}s grace "
                 "(wedged teardown); hard-exiting with the same status")
            try:
                sys.stdout.flush()
                sys.stderr.flush()
            except Exception:
                pass
            os._exit(code)

        t = threading.Timer(grace, _escalate)
        t.daemon = True
        t.start()
    sys.exit(code)


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _peak_flops(device_kind: str):
    # single source of truth shared with the train-loop telemetry
    from horovod_tpu.metrics.mfu import peak_flops
    return peak_flops(device_kind)


# -- per-phase timing (child side) -------------------------------------------
# Cumulative phase -> seconds, persisted to HVD_BENCH_PHASE_FILE at every
# boundary so a deadline-killed child still leaves a record of WHERE the
# wall clock went (device init vs compile vs measure). The file also names
# the phase in flight at kill time. Every emitted result doc embeds the
# same dict under "phases".
_PHASES = {}
_PHASE_IN_PROGRESS = None
# Latest provisional result doc, mirrored into the phase file so a
# SIGKILLed child (whose stdout pipe may die with it) still leaves its
# measured number where the parent can salvage it.
_PROVISIONAL_DOC = None


def _flush_phase_file() -> None:
    path = os.environ.get("HVD_BENCH_PHASE_FILE")
    if not path:
        return
    try:
        # atomic replace: a kill landing mid-write must not truncate the
        # record this side channel exists to preserve
        with open(path + ".tmp", "w") as f:
            json.dump({"phases": _PHASES,
                       "in_progress": _PHASE_IN_PROGRESS,
                       "provisional_result": _PROVISIONAL_DOC}, f)
        os.replace(path + ".tmp", path)
    except OSError:
        pass


def _begin_phase(name: str) -> float:
    global _PHASE_IN_PROGRESS
    _PHASE_IN_PROGRESS = name
    _flush_phase_file()
    return time.perf_counter()


def _end_phase(name: str, t0: float) -> float:
    global _PHASE_IN_PROGRESS
    dt = time.perf_counter() - t0
    _PHASES[name] = round(_PHASES.get(name, 0.0) + dt, 2)
    _PHASE_IN_PROGRESS = None
    _flush_phase_file()
    _log(f"phase {name}: {dt:.1f}s")
    return dt


def _measure_and_report(step_fn, state, readback, analytic_flops_per_device,
                        iters, per_step_units, n_chips, metric, unit,
                        vs_baseline_per_unit, extra,
                        hlo_flops_factor: int = 1,
                        late_extra=None) -> None:
    """Shared hardened measurement: warmup, a queued timing window bracketed
    by host readbacks (``jax.block_until_ready`` is unreliable on the axon
    relay platform — it can return before execution completes), per-device
    FLOPs from the compiled executable's ``cost_analysis()`` (post-SPMD, so
    per-device by construction; ``analytic_flops_per_device`` is the
    fallback), MFU vs the chip's peak, and the single JSON result line.

    ``step_fn(state) -> (state, loss)`` runs one training step;
    ``readback(state)`` forces completion of the queued chain;
    ``state.lowerable()`` returns ``(jitted, args)`` for cost analysis.

    A PROVISIONAL result line (same schema + ``"provisional": true``) is
    emitted from a short measured warmup window BEFORE the patient timing
    window, so a run killed by an external deadline still carries a real
    measured number (round-3 failure mode: cold compile through the relay
    out-waited the driver and the round shipped value=null).

    ``HVD_BENCH_ITERS`` overrides the final timing window's step count —
    contract tests on CPU shrink it (they assert the artifact schema, not
    timing precision); leave it unset for real measurements.
    """
    import jax

    try:
        iters = int(os.environ.get("HVD_BENCH_ITERS", "") or iters)
    except ValueError:
        pass

    # compile hooks (docs/OBSERVABILITY.md "Compile & memory
    # observability"): measured backend-compile seconds replace the old
    # wall-clock guess (compile_s also timed the first step's RUN)
    try:
        from horovod_tpu.profiling import compile_watch as _cw
        _cw.ensure_installed()
    except Exception as e:
        _cw = None
        _log(f"compile hooks unavailable ({e!r})")

    def _compile_seconds():
        if _cw is None:
            return None
        tot = _cw.totals()
        return round(tot["seconds_total"], 3) if tot["compiles"] else None

    def _hbm_peak():
        try:
            from horovod_tpu.profiling.memory import peak_bytes
            return peak_bytes()  # None on backends without memory_stats
        except Exception:
            return None

    def _guard_skipped():
        """Steps the numeric guardrail zeroed during this process
        (train/guard.py).  Recorded in the artifact so a benched run
        that silently skipped steps — doing less optimizer work per
        "step" — cannot pass as a clean perf number; ci/check_bench.py
        rejects a non-null value with skips."""
        try:
            from horovod_tpu.metrics.registry import default_registry
            c = default_registry().get("hvd_guard_skipped_steps_total")
            return int(c.value) if c is not None else 0
        except Exception:
            return 0

    # goodput ledger (docs/OBSERVABILITY.md "Goodput ledger"): the bench
    # loop brackets each step itself (it does not run StepTimer), so the
    # artifact carries the same closed-books account a training run
    # would — where the measured window's wall clock went, category by
    # category, plus the roofline decomposition of 1-MFU.  On CPU
    # children the categories are real but mfu stays null (no peak).
    try:
        from horovod_tpu.metrics import goodput as _gp
    except Exception as e:
        _gp = None
        _log(f"goodput ledger unavailable ({e!r})")

    def _goodput_doc(mfu):
        if _gp is None:
            return None, None
        try:
            from horovod_tpu.profiling import attribution
            snap = _gp.snapshot(flush_open=True)
            if snap is None:
                return None, None
            return snap, attribution.attribute(snap, mfu=mfu)
        except Exception as e:
            _log(f"goodput snapshot failed ({e!r})")
            return None, None

    def _tracing_enabled():
        """Whether causal tracing (HVD_TPU_TRACE) was live during the
        measurement.  Recorded so a standing perf number cannot
        SILENTLY pay for always-on tracing: ci/check_bench.py refuses
        a non-null value measured with tracing enabled unless the run
        says so out loud (HVD_BENCH_ALLOW_TRACING=1)."""
        try:
            from horovod_tpu.tracing import enabled
            return bool(enabled())
        except Exception:
            return False

    def emit(value, dt_window, n_iters, provisional, flops_per_device,
             flops_src, compile_s, series=None):
        peak = _peak_flops(jax.devices()[0].device_kind)
        mfu = (round(flops_per_device * n_iters / dt_window / peak, 4)
               if peak and flops_per_device else None)
        gp_snap, gp_att = _goodput_doc(mfu)
        # extra values may be callables of the per-chip rate
        ex = {k: (v(value) if callable(v) else v) for k, v in extra.items()}
        if not provisional and late_extra is not None:
            # expensive post-measurement extras (e.g. the pp=1
            # compute-only bubble baseline, which compiles a second
            # model): evaluated ONLY for the final line, AFTER the
            # provisional emits — a deadline kill mid-baseline must
            # never cost the provisional number (the round-3 lesson)
            try:
                ex.update(late_extra(value) or {})
            except Exception as e:
                _log(f"late extra failed ({e!r}); fields omitted")
        doc = {
            "metric": metric,
            "trace_dir": os.environ.get("HVD_BENCH_TRACE_DIR") or None,
            "value": round(value, 2),
            "unit": unit,
            "vs_baseline": round(value / vs_baseline_per_unit, 3)
            if vs_baseline_per_unit else None,
            "mfu": mfu,
            "flops_per_device_per_step": flops_per_device,
            "flops_source": flops_src,
            "n_chips": n_chips,
            "device_kind": jax.devices()[0].device_kind,
            "compile_s": round(compile_s, 1),
            "compile_seconds": _compile_seconds(),
            "hbm_peak_bytes": _hbm_peak(),
            "timing_iters": n_iters,
            "guard_skipped_steps": _guard_skipped(),
            "goodput": gp_snap,
            "mfu_attribution": gp_att,
            "tracing_enabled": _tracing_enabled(),
            "commit": _git_commit(),
            "phases": dict(_PHASES),
            **ex,
        }
        if series is not None:
            # per-iteration wall-clock gaps across the timing window
            # (on CPU each is a synced real step; on TPU they are
            # dispatch gaps, which still track device throughput once
            # the async queue saturates) — the TRAJECTORY, so
            # ci/check_bench.py can gate on drift inside the window,
            # not just the window mean (docs/OBSERVABILITY.md)
            doc["step_time_series"] = series
        if provisional:
            doc["provisional"] = True
            # side-channel mirror: the streamed stdout line survives a
            # SIGTERM, but a SIGKILL mid-pipe can lose it — the phase
            # file (atomic replace) cannot be half-lost
            global _PROVISIONAL_DOC
            _PROVISIONAL_DOC = doc
            _flush_phase_file()
        print(json.dumps(doc), flush=True)

    global _T_SETUP0
    if _T_SETUP0 is not None:
        # model/optimizer/data construction since the device_init phase
        _end_phase("setup", _T_SETUP0)
        _T_SETUP0 = None
    _log("compiling (first step)...")
    t_c0 = _begin_phase("compile")
    if _gp is not None:
        _gp.note_step_begin()
    state, loss = step_fn(state)
    readback(loss)
    compile_s = _end_phase("compile", t_c0)
    if _gp is not None:
        # the first step pays the compile; the compile_watch delta
        # claims that slice out of the in-step account
        _gp.note_step_end(compile_s)
    _log(f"first step (compile+run) took {compile_s:.1f}s; warmup window...")

    # XLA:CPU on a starved host (the 8-virtual-device test mesh on one
    # core) crashes/deadlocks when multi-device executions pile up
    # un-synced — with a WARM compile cache the dispatch is fast enough
    # to pile them reliably (the child_exits_cleanly "under load" flake:
    # heap corruption surfacing as mid-run SIGSEGV or a teardown
    # "double free" abort).  A per-step host sync serializes the queue;
    # CPU numbers are smoke, not perf, so the sync costs nothing real.
    # TPU keeps the async chain (queue depth IS the perf being measured).
    sync_every_step = jax.default_backend() == "cpu"

    # measured warmup window -> provisional results (analytic FLOPs:
    # cheap). The FIRST post-compile step is already a real measured
    # number, emitted IMMEDIATELY (stdout + the phase-file side channel)
    # — rounds 3-5 shipped value:null because the deadline landed between
    # compile and the end of the old 2-iter warmup window; now the
    # provisional window is one step, refined when full warmup lands.
    warmup_iters = 2
    t_w0 = _begin_phase("warmup")
    t_gp = time.perf_counter()
    for i in range(warmup_iters):
        if _gp is not None:
            _gp.note_step_begin()
        state, loss = step_fn(state)
        if sync_every_step or i == 0:
            readback(loss)
        if _gp is not None:
            now_gp = time.perf_counter()
            _gp.note_step_end(now_gp - t_gp)
            t_gp = now_gp
        if i == 0:
            dt_1 = time.perf_counter() - t_w0
            emit(per_step_units / dt_1 / n_chips, dt_1, 1,
                 provisional=True,
                 flops_per_device=analytic_flops_per_device(),
                 flops_src="analytic", compile_s=compile_s)
            _log(f"early provisional emitted (first step {dt_1:.2f}s)")
    readback(loss)
    dt_w = _end_phase("warmup", t_w0)
    emit(per_step_units * warmup_iters / dt_w / n_chips, dt_w, warmup_iters,
         provisional=True, flops_per_device=analytic_flops_per_device(),
         flops_src="analytic", compile_s=compile_s)
    _log(f"provisional refined (warmup {dt_w:.2f}s); timing...")

    # graceful self-deadline: a child the parent has to SIGTERM/SIGKILL
    # tears the PJRT chip claim down dirty and can wedge the relay lease
    # for the NEXT run (10-25 min); exiting cleanly with the provisional
    # already on stdout is strictly better than being killed mid-window.
    # The warmup window just measured the per-step cost, so PREDICT the
    # final window's duration instead of using a fixed margin — on a slow
    # relay day 10 steps can take minutes.
    deadline_epoch = float(os.environ.get("HVD_BENCH_CHILD_DEADLINE", "0"))
    est_final_s = dt_w / warmup_iters * iters
    if deadline_epoch and \
            time.time() + est_final_s + 45 > deadline_epoch:
        _log(f"skipping final window (predicted {est_final_s:.0f}s would "
             "cross the attempt deadline); provisional already emitted, "
             "exiting cleanly")
        _clean_exit(0)

    # --trace-dir / HVD_BENCH_TRACE_DIR: per-rank timeline shard over
    # the measured phase, merged into the artifact dir afterwards so a
    # perf regression ships with its trace (docs/OBSERVABILITY.md)
    tracer = _start_measure_trace()
    step_series = []
    t0 = _begin_phase("measure")
    t_prev = time.perf_counter()
    for i in range(iters):
        if tracer is not None:
            tracer.collective_begin("measure_step", "step", f"step#{i+1}")
        if _gp is not None:
            _gp.note_step_begin()
        state, loss = step_fn(state)
        if sync_every_step:
            readback(loss)
        if tracer is not None:
            tracer.collective_end("measure_step", f"step#{i+1}")
        t_now = time.perf_counter()
        if _gp is not None:
            _gp.note_step_end(t_now - t_prev)
        step_series.append(round(t_now - t_prev, 6))
        t_prev = t_now
    readback(loss)  # forces completion of the whole chain
    dt = _end_phase("measure", t0)
    _record_bench_series(step_series)
    _finish_measure_trace(tracer)
    _log(f"timing window {dt:.2f}s for {iters} iters")

    per_chip = per_step_units * iters / dt / n_chips

    flops_per_device = None
    flops_src = "hlo"
    try:
        jitted, args = state.lowerable()
        cost = jitted.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        # XLA's cost analysis counts a while-loop (lax.scan) body ONCE,
        # not trip-count times (verified empirically) — scale by the
        # in-graph step count so hlo- and analytic-sourced results agree
        flops_per_device = (float(cost.get("flops", 0.0))
                            * hlo_flops_factor) or None
    except Exception as e:
        _log(f"cost_analysis unavailable ({e!r}); using analytic FLOPs")
    if not flops_per_device:
        flops_per_device = analytic_flops_per_device()
        flops_src = "analytic"

    emit(per_chip, dt, iters, provisional=False,
         flops_per_device=flops_per_device, flops_src=flops_src,
         compile_s=compile_s, series=step_series)


# wall-clock start of model/data setup, stamped by _child() after device
# init; consumed (into the "setup" phase) by _measure_and_report
_T_SETUP0 = None


def _record_bench_series(step_series) -> None:
    """Persist the measured window's per-step trajectory into the
    observability history (HVD_TPU_OBS_DIR JSONL) — the same store the
    train-loop telemetry writes, so ``python -m horovod_tpu.metrics
    history`` reads bench runs too.  Best-effort: history must never
    fail the measurement."""
    try:
        from horovod_tpu.metrics import timeseries
        if not timeseries.obs_dir():
            return
        for i, dt in enumerate(step_series):
            timeseries.record_step(i + 1, dt, source="bench")
    except Exception as e:
        _log(f"bench series persistence failed ({e!r}); continuing")


def _start_measure_trace():
    """HVD_BENCH_TRACE_DIR (--trace-dir): open this rank's timeline
    shard for the measured phase. Returns the Timeline or None."""
    trace_dir = os.environ.get("HVD_BENCH_TRACE_DIR")
    if not trace_dir:
        return None
    try:
        from horovod_tpu.common.timeline import Timeline, shard_path
        os.makedirs(trace_dir, exist_ok=True)
        rank = int(os.environ.get(
            "HVD_TPU_RANK", os.environ.get("HOROVOD_RANK", "0")))
        tl = Timeline(rank)
        tl.start_shard(shard_path(trace_dir + os.sep, rank))
        _log(f"measure-phase trace shard: {trace_dir} (rank {rank})")
        return tl
    except Exception as e:  # tracing must never fail the measurement
        _log(f"trace-dir setup failed ({e!r}); continuing untraced")
        return None


def _finish_measure_trace(tracer) -> None:
    """Close the shard and merge every shard in the trace dir into
    ``merged_trace.json`` (multi-rank runs on a shared FS fold into one
    Perfetto trace; single-rank still yields a loadable artifact)."""
    if tracer is None:
        return
    try:
        tracer.stop()
        from horovod_tpu.diagnostics.merge import merge_directory
        out = merge_directory(os.environ["HVD_BENCH_TRACE_DIR"])
        if out:
            _log(f"merged measure-phase trace: {out}")
    except Exception as e:
        _log(f"trace merge failed ({e!r})")


class _Run:
    """Mutable step state + the (jitted, args) handle for cost analysis."""

    def __init__(self, jitted, *args):
        self.jitted = jitted
        self.args = list(args)

    def lowerable(self):
        return self.jitted, tuple(self.args)


def _wrap_compression(tx):
    """Wrap the optax optimizer per HVD_BENCH_COMPRESSION (the
    ``--compression`` flag): error-feedback quantized gradient sync
    through ``hvd.DistributedOptimizer`` (docs/PERF.md "Gradient
    compression"). Returns ``(tx, codec_name_or_None)``; the in-graph
    quantize∘dequantize cost lands in the measured step either way, so
    the number answers "what does the codec cost on this model".

    ``--autotune`` / HVD_BENCH_AUTOTUNE=1 additionally warm-starts the
    communication knobs from the persistent mesh-autotune plan cache
    (``DistributedOptimizer(autotune=True)``, docs/PERF.md
    "Autotuning") — a prior tuned run's bucket/codec choice lands in
    the measured step with zero search."""
    name = os.environ.get("HVD_BENCH_COMPRESSION", "").strip().lower()
    autotune = os.environ.get("HVD_BENCH_AUTOTUNE", "") not in ("", "0")
    if (not name or name == "none") and not autotune:
        return tx, None
    import horovod_tpu as hvd
    kw = {}
    if name and name != "none":
        from horovod_tpu.compression import (ErrorFeedback,
                                             resolve_compressor)
        kw["compression"] = ErrorFeedback(resolve_compressor(name))
        _log(f"gradient compression enabled: {name} (error feedback)")
    else:
        name = None
    if autotune:
        kw["autotune"] = True
        _log("autotune warm start enabled (plan cache: "
             f"{os.environ.get('HVD_TPU_AUTOTUNE_CACHE_DIR', '<unset>')})")
    return hvd.DistributedOptimizer(tx, **kw), name


def _child_bert() -> None:
    """BERT-Large pretraining throughput (HVD_BENCH_MODEL=bert)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models.bert import (Bert, bert_large, init_bert,
                                         make_bert_train_step)

    _log(f"devices: {jax.devices()}")
    hvd.init()
    mesh = hvd.build_mesh(dp=-1)
    n_chips = int(np.prod(list(mesh.shape.values())))

    B = int(os.environ.get("HVD_BENCH_BATCH", "64")) * n_chips
    S = int(os.environ.get("HVD_BENCH_SEQ", "128"))
    scan = max(1, int(os.environ.get("HVD_BENCH_SCAN", "8")))
    cfg = bert_large()
    model = Bert(cfg)
    params = init_bert(model, jax.random.PRNGKey(0), S, mesh)
    tx, compression = _wrap_compression(optax.adamw(1e-4))
    opt_state = jax.jit(tx.init)(params)
    step = make_bert_train_step(model, tx, mesh, scan_steps=scan)

    rng = np.random.RandomState(0)
    sh = hvd.batch_sharding(mesh)
    batch = {
        "input_ids": jax.device_put(jnp.asarray(
            rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32), sh),
        "token_type_ids": jax.device_put(jnp.zeros((B, S), jnp.int32), sh),
        "attention_mask": jax.device_put(jnp.ones((B, S), bool), sh),
        "mlm_labels": jax.device_put(jnp.asarray(
            rng.randint(0, cfg.vocab_size, (B, S)), jnp.int32), sh),
        "mlm_mask": jax.device_put(jnp.asarray(
            rng.rand(B, S) < 0.15, jnp.float32), sh),
        "nsp_labels": jax.device_put(jnp.asarray(
            rng.randint(0, 2, (B,)), jnp.int32), sh),
    }

    run = _Run(step, params, opt_state, batch)

    def step_fn(run):
        p, o, loss = run.jitted(run.args[0], run.args[1], run.args[2])
        run.args[0], run.args[1] = p, o
        return run, loss

    def analytic():
        # 6 * params * tokens (dense transformer training rule of thumb)
        n_params = sum(x.size
                       for x in jax.tree_util.tree_leaves(run.args[0]))
        return 6.0 * n_params * (B / n_chips) * S * scan

    _measure_and_report(
        step_fn, run, readback=float,
        analytic_flops_per_device=analytic, iters=10,
        per_step_units=B * scan,
        n_chips=n_chips, metric="bert_large_seqs_per_sec_per_chip",
        unit="seq/s/chip",
        vs_baseline_per_unit=None,  # reference publishes no BERT absolute
        extra={"batch_per_chip": B // n_chips, "seq_len": S,
               "scan_steps": scan, "compression": compression,
               "tokens_per_sec_per_chip": lambda v: round(v * S, 1)},
        hlo_flops_factor=scan)


def _child_gpt() -> None:
    """Decoder-only LM pretraining throughput on the flagship transformer
    (HVD_BENCH_MODEL=gpt): the model family behind the 5-axis parallel
    path (``horovod_tpu/models/transformer.py``). Defaults to a ~350M
    GPT-medium shape; HVD_BENCH_GPT_{LAYERS,DMODEL,HEADS,DFF}, HVD_BENCH_BATCH
    and HVD_BENCH_SEQ tune it.

    DP x PP pipelined training (docs/PERF.md "Pipeline parallelism"):
    ``HVD_BENCH_PP`` > 1 splits the mesh dp x pp and runs the decoder
    blocks as a compiled in-graph pipeline with
    ``HVD_BENCH_MICROBATCHES`` microbatches (default ``2*pp``).
    ``HVD_BENCH_SCHEDULE`` names the schedule; the transformer child
    runs ``gpipe`` (GPipe-by-autodiff — with a vocab-sized loss head an
    SPMD in-schedule 1F1B tail would pay the head on every stage every
    tick; the 1f1b/interleaved measurements live in
    ``benchmarks/pipeline_bench.py`` on layer-major models). The
    artifact records the locked parallelism plan, the analytic bubble
    fraction, and — from a short pp=1 compute-only baseline (the
    overlap_bench attribution pattern) — the MEASURED bubble
    (``bubble_measured``); ``ci/check_bench.py --pipeline`` gates the
    plan/analytic pair and prints both bubbles so drift is visible per
    round."""
    import numpy as np
    import jax
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models.transformer import (
        TransformerConfig, init_params, shard_params, make_train_step,
        init_opt_state, shard_batch)

    _log(f"devices: {jax.devices()}")
    hvd.init()
    pp = max(1, int(os.environ.get("HVD_BENCH_PP", "1") or 1))
    schedule = (os.environ.get("HVD_BENCH_SCHEDULE", "").strip().lower()
                or "gpipe")
    from horovod_tpu.parallel.plan import SCHEDULES
    if schedule not in SCHEDULES:
        raise ValueError(f"HVD_BENCH_SCHEDULE={schedule!r}; expected one "
                         f"of {SCHEDULES}")
    if pp > 1 and schedule != "gpipe":
        raise ValueError(
            "the gpt child's in-graph transformer pipeline is "
            "GPipe-by-autodiff; for measured 1f1b/interleaved schedules "
            "run benchmarks/pipeline_bench.py (layer-major models)")
    mesh = hvd.build_mesh(dp=-1, pp=pp)
    n_chips = int(np.prod(list(mesh.shape.values())))
    n_micro = int(os.environ.get("HVD_BENCH_MICROBATCHES", "0") or 0) \
        or (2 * pp if pp > 1 else 1)

    cfg = TransformerConfig(
        vocab_size=32000,
        d_model=int(os.environ.get("HVD_BENCH_GPT_DMODEL", "1024")),
        n_heads=int(os.environ.get("HVD_BENCH_GPT_HEADS", "16")),
        n_layers=int(os.environ.get("HVD_BENCH_GPT_LAYERS", "24")),
        d_ff=int(os.environ.get("HVD_BENCH_GPT_DFF", "4096")),
        max_seq=int(os.environ.get("HVD_BENCH_SEQ", "2048")),
        n_microbatches=n_micro)
    if cfg.n_layers % pp != 0:
        raise ValueError(f"HVD_BENCH_PP={pp} must divide "
                         f"{cfg.n_layers} layers")
    B = int(os.environ.get("HVD_BENCH_BATCH", "8")) * n_chips
    S = cfg.max_seq
    dp = n_chips // pp
    if pp > 1 and (B // dp) % n_micro != 0:
        raise ValueError(
            f"per-replica batch {B}/{dp} not divisible by "
            f"HVD_BENCH_MICROBATCHES={n_micro}")

    params = shard_params(init_params(np.random.RandomState(0), cfg,
                                      n_stages=pp),
                          cfg, mesh)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    _log(f"gpt params: {n_params/1e6:.1f}M, batch {B} x seq {S}")
    tx, compression = _wrap_compression(optax.adamw(1e-4))
    opt_state = init_opt_state(tx, params, mesh, cfg)
    scan = max(1, int(os.environ.get("HVD_BENCH_SCAN", "8")))
    step = make_train_step(cfg, mesh, tx, scan_steps=scan)

    rng = np.random.RandomState(0)
    tokens_np = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    targets_np = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    tokens, targets = shard_batch(tokens_np, targets_np, mesh)

    run = _Run(step, params, opt_state, tokens, targets)

    def step_fn(run):
        p, o, loss, aux = run.jitted(*run.args)
        run.args[0], run.args[1] = p, o
        return run, loss

    # measured bubble (ISSUE 12 satellite): the same model + GLOBAL
    # batch at pp=1 does exactly the pipelined run's per-device compute
    # with zero pipeline dependencies — the overlap_bench attribution
    # pattern (compute-only vs full step).  Evaluated as a LATE extra:
    # it compiles a second full model, and that must happen after the
    # provisional emits and the main timing window, never before (a
    # deadline kill mid-baseline must not re-create the value=null
    # rounds the provisional emit exists to prevent).  Any failure just
    # leaves bubble_measured unrecorded.
    def _late_bubble(v):
        if pp <= 1 or not v:
            return {}
        import time as _time
        deadline = float(os.environ.get("HVD_BENCH_CHILD_DEADLINE",
                                        "0"))
        if deadline:
            # the baseline costs roughly one more model compile; the
            # compile watcher measured what this process has paid so
            # far — if a repeat would cross the attempt deadline, the
            # final line (already complete without bubble_measured)
            # matters more than the attribution anchor
            try:
                from horovod_tpu.profiling import compile_watch
                est = compile_watch.totals()["seconds_total"] + 60.0
            except Exception:
                est = 300.0
            if _time.time() + est > deadline:
                _log("skipping compute-only baseline (attempt deadline "
                     "too close)")
                return {}
        mesh1 = hvd.build_mesh(dp=-1)
        params1 = shard_params(init_params(
            np.random.RandomState(0), cfg, n_stages=1), cfg, mesh1)
        opt_state1 = init_opt_state(tx, params1, mesh1, cfg)
        step1 = make_train_step(cfg, mesh1, tx, scan_steps=scan)
        tok1, tgt1 = shard_batch(tokens_np, targets_np, mesh1)
        p1, o1, loss1, _aux = step1(params1, opt_state1, tok1, tgt1)
        float(loss1)                          # compile + warmup
        t0 = _time.perf_counter()
        for _ in range(3):
            p1, o1, loss1, _aux = step1(p1, o1, tok1, tgt1)
        float(loss1)  # host readback: block_until_ready lies on axon
        t_c = (_time.perf_counter() - t0) / 3
        _log(f"compute-only (pp=1) step: {t_c:.4f}s")
        # v is tokens/s/chip; the pipelined step time follows from the
        # per-step unit count
        t_pipe = (B * S * scan) / (v * n_chips)
        measured = max(0.0, min(1.0, 1.0 - t_c / t_pipe))
        from horovod_tpu.train.pipeline import record_measured_bubble
        record_measured_bubble(measured)
        return {"compute_step_s": round(t_c, 5),
                "bubble_measured": round(measured, 4)}

    from horovod_tpu.parallel.pipeline import bubble_fraction
    _measure_and_report(
        step_fn, run, readback=float,
        analytic_flops_per_device=lambda:
            6.0 * n_params * (B / n_chips) * S * scan,
        iters=10, per_step_units=B * S * scan, n_chips=n_chips,
        metric="gpt_tokens_per_sec_per_chip", unit="tokens/s/chip",
        vs_baseline_per_unit=None,  # reference publishes no LM absolute
        extra={"batch_per_chip": B // n_chips, "seq_len": S,
               "scan_steps": scan, "compression": compression,
               "n_params_m": round(n_params / 1e6, 1),
               # the locked parallelism plan + its analytic bubble
               # (ci/check_bench.py --pipeline gates the pair)
               "parallel_plan": {
                   "dp": dp, "pp": pp, "schedule": schedule,
                   "n_microbatches": n_micro, "virtual_stages": 1},
               "bubble_fraction": round(
                   bubble_fraction(schedule, pp, n_micro), 4)},
        hlo_flops_factor=scan,
        late_extra=_late_bubble)


def _child_cnn(which: str) -> None:
    """Synthetic CNN throughput: resnet50 (the headline), resnet101,
    vgg16, or inception3 — the reference's full published benchmark
    model set (``docs/benchmarks.rst:13-14``)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.models.resnet import (ResNet50, ResNet101,
                                           create_resnet_state,
                                           make_resnet_train_step,
                                           batch_sharding)
    from horovod_tpu.models.vgg import (VGG16, create_vgg_state,
                                        make_vgg_train_step)
    from horovod_tpu.models.inception import (InceptionV3,
                                              create_inception_state,
                                              make_inception_train_step)

    _log(f"devices: {jax.devices()}")
    hvd.init()
    mesh = hvd.build_mesh(dp=-1)
    n_chips = int(np.prod(list(mesh.shape.values())))

    batch_per_chip = int(os.environ.get(
        "HVD_BENCH_BATCH", "128" if which in ("vgg16", "inception3")
        else "256"))
    B = batch_per_chip * n_chips
    image_size = 299 if which == "inception3" else 224
    # MLPerf-style space-to-depth stem by default: the 7x7/s2 conv over
    # C=3 wastes 4x of the MXU's input-channel tiling (docs/PERF.md);
    # HVD_BENCH_STEM=conv selects the textbook stem for comparison.
    stem = os.environ.get("HVD_BENCH_STEM", "s2d")
    # In-graph multi-step (lax.scan): one dispatch covers the chain, so
    # host->device launch latency (significant through the relay) is off
    # the critical path and the number reflects device throughput.
    scan = max(1, int(os.environ.get("HVD_BENCH_SCAN", "8")))

    has_batch_stats = True
    if which == "vgg16":
        model = VGG16(num_classes=1000, dtype=jnp.bfloat16)
        params = create_vgg_state(model, jax.random.PRNGKey(0),
                                  image_size=image_size, mesh=mesh)
        batch_stats = None
        has_batch_stats = False
        tx, compression = _wrap_compression(optax.sgd(0.01, momentum=0.9))
        opt_state = jax.jit(tx.init)(params)
        step = make_vgg_train_step(model, tx, mesh, scan_steps=scan)
        extra = {"batch_per_chip": batch_per_chip, "scan_steps": scan,
                 "compression": compression}
    elif which == "inception3":
        model = InceptionV3(num_classes=1000, dtype=jnp.bfloat16)
        params, batch_stats = create_inception_state(
            model, jax.random.PRNGKey(0), image_size=image_size, mesh=mesh)
        tx, compression = _wrap_compression(optax.sgd(0.1, momentum=0.9))
        opt_state = jax.jit(tx.init)(params)
        step = make_inception_train_step(model, tx, mesh, scan_steps=scan)
        extra = {"batch_per_chip": batch_per_chip,
                 "image_size": image_size, "scan_steps": scan,
                 "compression": compression}
    else:
        mk = ResNet101 if which == "resnet101" else ResNet50
        # HVD_BENCH_REMAT=1: jax.checkpoint each block — HBM for
        # recompute, for exploring larger per-chip batches (PERF.md (b)).
        # Inside a scanned chain the CSE barrier is unnecessary (flax
        # docs) and costs — drop it when scan_steps > 1.
        remat = os.environ.get("HVD_BENCH_REMAT", "0") == "1"
        model = mk(num_classes=1000, dtype=jnp.bfloat16, stem=stem,
                   remat=remat, remat_prevent_cse=scan <= 1)
        params, batch_stats = create_resnet_state(
            model, jax.random.PRNGKey(0), image_size=image_size, mesh=mesh)
        tx, compression = _wrap_compression(optax.sgd(0.1, momentum=0.9))
        opt_state = jax.jit(tx.init)(params)
        step = make_resnet_train_step(model, tx, mesh, scan_steps=scan)
        extra = {"batch_per_chip": batch_per_chip, "stem": stem,
                 "scan_steps": scan, "remat": remat,
                 "compression": compression}

    rng = np.random.RandomState(0)
    images = jax.device_put(
        jnp.asarray(rng.rand(B, image_size, image_size, 3), jnp.bfloat16),
        batch_sharding(mesh))
    labels = jax.device_put(
        jnp.asarray(rng.randint(0, 1000, (B,)), jnp.int32),
        batch_sharding(mesh))

    # vgg16/inception3 take a step_idx that folds into the dropout key;
    # thread a real counter so the measurement draws a fresh mask per step
    # (a traced scalar: varying it does not recompile)
    step_counter = iter(range(10 ** 9))
    if not has_batch_stats:
        run = _Run(step, params, opt_state, images, labels)

        def step_fn(run):
            if which == "vgg16":
                p, o, loss = run.jitted(*run.args,
                                        step_idx=next(step_counter))
            else:
                p, o, loss = run.jitted(*run.args)
            run.args[0], run.args[1] = p, o
            return run, loss
    else:
        run = _Run(step, params, batch_stats, opt_state, images, labels)

        def step_fn(run):
            if which == "inception3":
                p, bs, o, loss = run.jitted(*run.args,
                                            step_idx=next(step_counter))
            else:
                p, bs, o, loss = run.jitted(*run.args)
            run.args[0], run.args[1], run.args[2] = p, bs, o
            return run, loss

    _measure_and_report(
        step_fn, run, readback=float,
        # per dispatch = scan optimizer steps
        analytic_flops_per_device=lambda:
            3 * 2 * FWD_MACS_PER_IMG[which] * B * scan / n_chips,
        iters=20, per_step_units=B * scan, n_chips=n_chips,
        hlo_flops_factor=scan,
        metric=f"{which}_images_per_sec_per_chip", unit="img/s/chip",
        # the published 1656.82/16 figure is a ResNet-101 measurement
        # (docs/benchmarks.rst:32-43): it is the apples-to-apples baseline
        # for resnet101 and the customary headline denominator for
        # resnet50 (the only absolute number the reference publishes)
        vs_baseline_per_unit=REFERENCE_IMG_PER_SEC_PER_DEVICE
        if which in ("resnet50", "resnet101") else None,
        extra=extra)


def _child_resnet50_bare() -> None:
    """CONTROL RUN (HVD_BENCH_MODEL=resnet50_bare): the identical
    ResNet-50 in plain flax + optax + ``jax.jit`` — no ``hvd.init``, no
    mesh, no shardings, no framework train-step wrapper, no horovod_tpu
    collectives. Quantifies the framework's single-chip overhead: if this
    control lands within ~3% of the framework number, the measured MFU is
    the model/XLA ceiling, not framework tax (VERDICT r3, weak #2).

    The flax module class is imported for architecture identity — it is
    pure flax with zero framework coupling (``models/resnet.py``); the
    training step below is written from scratch here."""
    import functools

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    from horovod_tpu.models.resnet import ResNet50

    _log(f"devices: {jax.devices()}")
    dev = jax.devices()[0]

    batch = int(os.environ.get("HVD_BENCH_BATCH", "256"))
    stem = os.environ.get("HVD_BENCH_STEM", "s2d")
    scan = max(1, int(os.environ.get("HVD_BENCH_SCAN", "8")))
    # the control honors the SAME remat knob so framework-vs-bare always
    # compares identical programs (apples-to-apples promise)
    remat = os.environ.get("HVD_BENCH_REMAT", "0") == "1"
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16, stem=stem,
                     remat=remat, remat_prevent_cse=scan <= 1)
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3), jnp.bfloat16),
        train=True)
    params, batch_stats = variables["params"], variables["batch_stats"]
    tx = optax.sgd(0.1, momentum=0.9)
    opt_state = jax.jit(tx.init)(params)

    def one_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": batch_stats}, images,
                train=True, mutable=["batch_stats"])
            one_hot = jax.nn.one_hot(labels, logits.shape[-1])
            loss = optax.softmax_cross_entropy(logits, one_hot).mean()
            return loss, mut["batch_stats"]
        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step(params, batch_stats, opt_state, images, labels):
        # same in-graph multi-step as the framework path, so the control
        # stays apples-to-apples (one dispatch per scan-step chain)
        if scan == 1:
            return one_step(params, batch_stats, opt_state, images, labels)

        def body(carry, _):
            p, bs, o = carry
            p, bs, o, loss = one_step(p, bs, o, images, labels)
            return (p, bs, o), loss
        (params, batch_stats, opt_state), losses = jax.lax.scan(
            body, (params, batch_stats, opt_state), None, length=scan)
        return params, batch_stats, opt_state, losses[-1]

    rng = np.random.RandomState(0)
    images = jax.device_put(jnp.asarray(
        rng.rand(batch, 224, 224, 3), jnp.bfloat16), dev)
    labels = jax.device_put(jnp.asarray(
        rng.randint(0, 1000, (batch,)), jnp.int32), dev)

    run = _Run(step, params, batch_stats, opt_state, images, labels)

    def step_fn(run):
        p, bs, o, loss = run.jitted(*run.args)
        run.args[0], run.args[1], run.args[2] = p, bs, o
        return run, loss

    _measure_and_report(
        step_fn, run, readback=float,
        analytic_flops_per_device=lambda:
            3 * 2 * FWD_MACS_PER_IMG["resnet50"] * batch * scan,
        iters=20, per_step_units=batch * scan, n_chips=1,
        hlo_flops_factor=scan,
        metric="resnet50_bare_images_per_sec_per_chip", unit="img/s/chip",
        vs_baseline_per_unit=REFERENCE_IMG_PER_SEC_PER_DEVICE,
        extra={"batch_per_chip": batch, "stem": stem, "scan_steps": scan,
               "remat": remat, "control": True})


def _enable_compile_cache() -> None:
    """Point JAX's persistent compilation cache at a repo-local dir so
    retries and successive driver rounds compile warm. A cold ResNet-50
    compile through the relay can exceed the driver's deadline; with the
    cache populated it is seconds. Harmless no-op if the backend doesn't
    support the cache.

    CPU children skip it: executing a warm-cache (deserialized) program
    on the 8-virtual-device XLA:CPU test mesh intermittently corrupts
    the heap (mid-run SIGSEGV or a teardown "double free" abort — the
    child_exits_cleanly flake; conftest.py records the same
    cache-on-only crash signature for the test suite), and a CPU
    child's compile is seconds anyway."""
    import jax
    # platform read from config, NOT default_backend(): backend init
    # must stay inside the attributable device_init phase (and on TPU
    # it claims the chips — minutes through a busy relay)
    platforms = str(getattr(jax.config, "jax_platforms", "") or "")
    if platforms.split(",")[0].strip() == "cpu":
        _log("persistent compile cache skipped on CPU (warm-cache "
             "XLA:CPU executions are unstable on the virtual test mesh)")
        return
    cache_dir = os.environ.get(
        "HVD_BENCH_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache EVERY entry: the driver's cold run must find the step
        # function warm no matter how fast it compiled for the builder
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _log(f"persistent compile cache at {cache_dir}")
    except Exception as e:  # cache is an optimization, never a failure
        _log(f"compile cache unavailable: {e!r}")


def _child() -> None:
    """Run the actual measurement; print the result JSON line to stdout."""
    # honor an explicit JAX_PLATFORMS over any sitecustomize that force-
    # selects the TPU plugin: a CPU-targeted child must never hang waiting
    # on the TPU relay (env var alone loses to a config.update made at
    # interpreter startup)
    global _T_SETUP0
    if os.environ.get("JAX_PLATFORMS"):
        import jax
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    _enable_compile_cache()
    # device_init: first backend touch claims the chips (through the TPU
    # relay this alone can eat minutes — make it attributable)
    t0 = _begin_phase("device_init")
    import jax
    jax.devices()
    _end_phase("device_init", t0)
    # setup phase (model/optimizer/data construction) stays open until
    # _measure_and_report closes it — a kill in here must be attributable
    _T_SETUP0 = _begin_phase("setup")
    which = os.environ.get("HVD_BENCH_MODEL", "resnet50").lower()
    if which in ("bert", "bert_large"):  # zoo key and short form
        _child_bert()
    elif which in ("gpt", "transformer"):
        _child_gpt()
    elif which == "resnet50_bare":
        _child_resnet50_bare()
    elif which in ("resnet50", "resnet101", "vgg16", "inception3"):
        _child_cnn(which)
    else:
        _no_such_model(which)
    # result line is on stdout; don't let a wedged or crashing
    # interpreter teardown turn this clean run into a parent TERM->KILL
    # (and a wedged relay lease for the NEXT run)
    _clean_exit(0)


def _no_such_model(which: str) -> None:
    # rc 2 = deterministic config error; the parent fails fast
    # instead of retrying
    _log(f"unknown HVD_BENCH_MODEL={which!r}; expected "
         "resnet50|resnet50_bare|resnet101|vgg16|inception3|bert|gpt")
    sys.exit(2)


# Latest per-phase timing record recovered from a child (via its
# HVD_BENCH_PHASE_FILE), so even a deadline-killed attempt's failure JSON
# says where the wall clock went: {"phases": {...}, "in_progress": name}.
_LAST_PHASES = None


def _read_phase_file(path) -> None:
    global _LAST_PHASES
    try:
        with open(path) as f:
            doc = json.load(f)
        # a child killed INSIDE its first phase has phases == {} but
        # in_progress set — that record is the whole point (it names the
        # phase that ate the deadline, e.g. a wedged device_init)
        if isinstance(doc, dict) and (doc.get("phases") or
                                      doc.get("in_progress") or
                                      doc.get("provisional_result")):
            _LAST_PHASES = doc
    except (OSError, ValueError):
        pass
    try:
        os.unlink(path)
    except OSError:
        pass


def _attach_phases(doc: dict) -> dict:
    """Fold the recovered per-phase timings into an outgoing result doc
    (no-op for docs that already carry their own "phases")."""
    if "phases" not in doc:
        doc["phases"] = (_LAST_PHASES or {}).get("phases", {})
    in_progress = (_LAST_PHASES or {}).get("in_progress")
    if in_progress and "phase_in_progress" not in doc:
        doc["phase_in_progress"] = in_progress
    return doc


def _run_attempt(deadline_s):
    """Run one child attempt, STREAMING its stdout so lines emitted before
    a deadline kill survive. Returns ``(final_line | None,
    provisional_line | None, error | None)`` — ``final_line`` is the
    non-provisional result; ``provisional_line`` the warmup-window one."""
    import tempfile
    lines = []
    env = dict(os.environ)
    # causal tracing pinned OFF for the measured child unless the
    # caller set it explicitly: the standing perf number must not
    # silently pay for tracing — the artifact's tracing_enabled field
    # + ci/check_bench.py enforce it (child-env only: bench.main() is
    # also called in-process by the contract tests, and mutating the
    # caller's environ would leak into unrelated code)
    env.setdefault("HVD_TPU_TRACE", "0")
    # child exits cleanly 90s before we would have to kill it (a killed
    # TPU child can wedge the relay lease for the following run)
    env["HVD_BENCH_CHILD_DEADLINE"] = str(time.time() + deadline_s - 90)
    # side-channel for per-phase timings: survives a SIGKILLed child
    phase_fd, phase_path = tempfile.mkstemp(prefix="hvd_bench_phases_",
                                            suffix=".json")
    os.close(phase_fd)
    env["HVD_BENCH_PHASE_FILE"] = phase_path
    proc = subprocess.Popen(
        [sys.executable, "-u", os.path.abspath(__file__), "--child"],
        stdout=subprocess.PIPE, stderr=sys.stderr, text=True, bufsize=1,
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)))

    def _drain(pipe):
        try:
            for line in pipe:
                lines.append(line)
        except (ValueError, OSError):
            pass  # parent closed the pipe out from under us: done

    reader = threading.Thread(target=_drain, args=(proc.stdout,),
                              daemon=True)
    reader.start()
    timed_out = False
    try:
        proc.wait(timeout=deadline_s)
    except subprocess.TimeoutExpired:
        timed_out = True
        # SIGTERM first so the PJRT client can tear down its chip claim;
        # if the child is wedged in native init (SIGTERM deferred), we
        # MUST escalate to SIGKILL: an abandoned live child keeps
        # contending for the chip and starves every later attempt — a
        # worse outcome than a relay lease that has to expire.
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()
            try:
                # a child wedged in uninterruptible native I/O may defer
                # even SIGKILL until the syscall returns — reap with a
                # bound so the retry loop keeps its own schedule
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
    if not timed_out:
        # clean child exit: the writer side is closed, so the drain thread
        # hits EOF on its own — let it finish before touching the pipe, or
        # a close here can interrupt it mid-iteration and drop buffered
        # lines (including the final result JSON)
        reader.join(timeout=10)
    # closing our end of the pipe unblocks the drain thread even if a
    # grandchild inherited the write end and never exits (the reader gets
    # EBADF/EOF instead of blocking forever, and we stop leaking an fd +
    # thread per attempt)
    try:
        proc.stdout.close()
    except OSError:
        pass
    reader.join(timeout=10)

    _read_phase_file(phase_path)

    final = provisional = None
    for line in list(lines):  # snapshot: drain thread may yet be alive
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            if parsed.get("provisional"):
                provisional = line.strip()
            else:
                final = line.strip()
    if final is not None:
        return final, provisional, None
    if timed_out:
        return None, provisional, \
            f"attempt exceeded {deadline_s:.0f}s deadline"
    tail = "".join(lines).strip().splitlines()[-5:]
    err = f"child rc={proc.returncode}: " + " | ".join(tail)[-600:]
    if proc.returncode == 2:  # deterministic config error: do not retry
        err = "config error (no retry): " + err
    return None, provisional, err


def _failure_identity():
    """Metric name/unit for the failure JSON, matching the selected model.
    Unknown model names keep their own (unmintable) metric so a typo is
    never recorded as a real benchmark's failure."""
    which = os.environ.get("HVD_BENCH_MODEL", "resnet50").lower()
    if which in ("bert", "bert_large"):
        return "bert_large_seqs_per_sec_per_chip", "seq/s/chip"
    if which in ("gpt", "transformer"):
        return "gpt_tokens_per_sec_per_chip", "tokens/s/chip"
    if which == "resnet50_bare":
        return "resnet50_bare_images_per_sec_per_chip", "img/s/chip"
    if which in FWD_MACS_PER_IMG:
        return f"{which}_images_per_sec_per_chip", "img/s/chip"
    return f"unknown_model_{which}", "n/a"


def main() -> None:
    # One patient attempt sized to the whole remaining budget; further
    # attempts happen only if the first one failed FAST (backend init
    # error etc.) and real budget remains. Total wall-clock is hard-capped
    # at TOTAL_BUDGET_S — the fallback JSON always lands before the cap.
    t_start = time.monotonic()
    errors = []
    attempts_run = 0
    best_provisional = None
    while attempts_run < MAX_ATTEMPTS:
        # reserve covers: fallback emission + the kill/reap path inside
        # _run_attempt (terminate wait 60s + SIGKILL reap 30s = 90s),
        # which runs AFTER the attempt deadline expires
        remaining = TOTAL_BUDGET_S - FALLBACK_RESERVE_S - 90 - \
            (time.monotonic() - t_start)
        if remaining < 120:
            if not errors:
                errors.append(
                    "insufficient budget for an attempt "
                    f"(HVD_BENCH_TOTAL_BUDGET_S={TOTAL_BUDGET_S:.0f})")
            break  # not enough budget for a meaningful attempt
        attempts_run += 1
        line, provisional, err = _run_attempt(deadline_s=remaining)
        if line is not None:
            print(json.dumps(_attach_phases(json.loads(line))), flush=True)
            return
        if provisional is not None:
            best_provisional = provisional
        errors.append(f"attempt {attempts_run}: {err}")
        print(f"[bench] {errors[-1]}", file=sys.stderr, flush=True)
        if err.startswith("config error"):
            break
        if attempts_run < MAX_ATTEMPTS:
            time.sleep(BACKOFF_S)
    if best_provisional is None:
        # stdout lost the provisional line (SIGKILL mid-pipe) but the
        # phase-file side channel may still carry it
        salvaged = (_LAST_PHASES or {}).get("provisional_result")
        if salvaged:
            best_provisional = json.dumps(salvaged)
    if best_provisional is not None:
        # The warmup window produced a REAL measured throughput before the
        # attempt was cut short — that beats a value:null artifact. The
        # line keeps "provisional": true and gains the failure context.
        doc = json.loads(best_provisional)
        doc["note"] = ("final timing window did not complete: "
                       + "; ".join(errors)[-400:])
        print(json.dumps(_attach_phases(doc)), flush=True)
        return
    # Persistent failure: still emit one parseable JSON line, rc 0.
    # last_measured carries the most recent REAL-hardware result for this
    # metric (from the committed measurement log) so a relay outage at
    # capture time doesn't erase the perf evidence — value stays null and
    # error stays set: this is provenance, not a substitute measurement.
    metric, unit = _failure_identity()
    last = None
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BENCH_MEASURED.json")) as f:
            for run in json.load(f).get("runs", []):
                if run.get("result", {}).get("metric") == metric:
                    if last is None or run.get("measured_at", "") > \
                            last.get("measured_at", ""):
                        last = run
    except (OSError, ValueError, KeyError):
        pass
    print(json.dumps(_attach_phases({
        "metric": metric,
        "value": None,
        "unit": unit,
        "vs_baseline": None,
        "mfu": None,
        "error": "; ".join(errors)[-800:],
        "attempts": attempts_run,
        "last_measured": last,
    })), flush=True)


if __name__ == "__main__":
    # --compression int8|fp8|onebit|fp16|bf16: error-feedback gradient
    # compression in the measured step (env HVD_BENCH_COMPRESSION is the
    # equivalent knob and the parent→child channel)
    if "--compression" in sys.argv:
        i = sys.argv.index("--compression")
        if i + 1 >= len(sys.argv):
            print("[bench] --compression requires a value (int8|fp8|"
                  "onebit|fp16|bf16|none)", file=sys.stderr)
            sys.exit(2)
        os.environ["HVD_BENCH_COMPRESSION"] = sys.argv[i + 1]
    # --autotune: warm-start communication knobs from the persistent
    # mesh-autotune plan cache (HVD_TPU_AUTOTUNE_CACHE_DIR) in every
    # child (docs/PERF.md "Autotuning")
    if "--autotune" in sys.argv:
        os.environ["HVD_BENCH_AUTOTUNE"] = "1"
    # --trace-dir DIR: per-rank timeline shards during the measured
    # phase, merged into DIR/merged_trace.json (env channel:
    # HVD_BENCH_TRACE_DIR — inherited by the measurement child)
    if "--trace-dir" in sys.argv:
        i = sys.argv.index("--trace-dir")
        if i + 1 >= len(sys.argv):
            print("[bench] --trace-dir requires a directory",
                  file=sys.stderr)
            sys.exit(2)
        os.environ["HVD_BENCH_TRACE_DIR"] = sys.argv[i + 1]
    if "--child" in sys.argv:
        _child()
    else:
        main()
