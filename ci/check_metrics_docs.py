#!/usr/bin/env python
"""Metrics <-> docs lint (ISSUE 7 satellite): every metric name the tree
registers must appear in ``docs/OBSERVABILITY.md``, and every metric the
docs name must still exist in the tree.

Extraction is static: a registration is a string literal passed as the
first argument of a ``.counter(`` / ``.gauge(`` / ``.histogram(`` call
(the registry API), of the fleet renderer's ``g(`` helper
(``metrics/fleet.py`` synthesizes its breakdown gauges directly into the
snapshot), or of an exception-proofing ``_metric(`` wrapper
(``runner/kv_relay.py``).  F-string placeholders (``f"hvd_{unit}_total"``) become
wildcards, matched against the docs' ``hvd_<unit>_total`` convention
(``<...>`` also becomes a wildcard); histograms implicitly export
``_bucket``/``_sum``/``_count`` sub-series, so those suffixes are
stripped before matching a docs mention back to code.

Exit 0 = in sync. Exit 1 prints each missing/stale name. Run from CI
(``tests/test_metrics_docs.py`` wraps it) or by hand:

    python ci/check_metrics_docs.py [--list]
"""

from __future__ import annotations

import fnmatch
import os
import re
import sys
from typing import Dict, List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "OBSERVABILITY.md")

# where registrations live (tests register throwaway names on purpose)
SCAN_ROOTS = ("horovod_tpu", "benchmarks")
SCAN_FILES = ("bench.py", "__graft_entry__.py")

_REG_CALL = re.compile(
    r'(?:\.(?:counter|gauge|histogram)|\bg|\b_metric)\('
    r'\s*(f?)"(hvd_[^"]+)"', re.S)
# docs mention: hvd_name, hvd_<unit>_name, hvd_engine_* ... optionally
# followed by a {label=...} part (stripped)
_DOC_NAME = re.compile(r"\bhvd_[A-Za-z0-9_<>*]*[A-Za-z0-9_>*]")

# C API symbols, file/dir names etc. that look like metrics but are not
# registry instruments; docs name them in other contexts
_NOT_METRICS = {"hvd_engine_state_json", "hvd_stragglers_json",
                "hvd_timeline_mark", "hvd_timeline_enabled",
                "hvd_counters_json", "hvd_shutdown_force",
                "hvd_mfu_registered",
                "hvd_autopsy",        # the autopsy bundle directory
                "hvd_profile",        # the trace-capture retention dir
                "hvd_flight_rank*"}   # crash flight-dump filenames
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _norm_code(name: str, is_fstring: bool) -> str:
    if is_fstring:
        name = re.sub(r"\{[^}]*\}", "*", name)
    return name


def _norm_doc(tok: str) -> str:
    return re.sub(r"<[^>]*>", "*", tok)


def code_metrics() -> Dict[str, List[str]]:
    """{normalized metric pattern: [file:line, ...]} from the tree."""
    out: Dict[str, List[str]] = {}
    paths = [os.path.join(REPO, f) for f in SCAN_FILES]
    for root in SCAN_ROOTS:
        for dirpath, _dirs, files in os.walk(os.path.join(REPO, root)):
            paths.extend(os.path.join(dirpath, f) for f in files
                         if f.endswith(".py"))
    for path in paths:
        try:
            with open(path) as f:
                text = f.read()
        except OSError:
            continue
        for m in _REG_CALL.finditer(text):
            name = _norm_code(m.group(2), bool(m.group(1)))
            line = text[:m.start()].count("\n") + 1
            rel = os.path.relpath(path, REPO)
            out.setdefault(name, []).append(f"{rel}:{line}")
    return out


def doc_metrics() -> Set[str]:
    with open(DOC) as f:
        text = f.read()
    return {_norm_doc(tok) for tok in _DOC_NAME.findall(text)}


def _pattern_match(a: str, b: str) -> bool:
    """Either side may carry ``*`` wildcards."""
    return a == b or fnmatch.fnmatchcase(a, b) or fnmatch.fnmatchcase(b, a)


def _doc_covers_code(name: str, d: str) -> bool:
    """Does doc mention ``d`` document code metric ``name``?  A doc
    wildcard must carry a meaningful literal prefix (``hvd_engine_*``
    yes, the fully generic ``hvd_*_total`` from the per-unit naming
    convention no) — otherwise one generic mention would 'document'
    every future counter and the lint would never fire again."""
    if name == d:
        return True
    if "*" in d:
        prefix = d.split("*", 1)[0]
        return len(prefix) > len("hvd_") and \
            fnmatch.fnmatchcase(name, d)
    return False


def check() -> Tuple[List[str], List[str], Dict[str, List[str]]]:
    """Returns (undocumented code metrics, stale doc metrics, all code
    metrics with their registration sites)."""
    code = code_metrics()
    docs = doc_metrics()
    undocumented = [
        name for name in sorted(code)
        if not any(_doc_covers_code(name, d) for d in docs)]

    def in_code(doc_name: str) -> bool:
        candidates = [doc_name]
        for suf in _HIST_SUFFIXES:  # histogram sub-series in examples
            if doc_name.endswith(suf):
                candidates.append(doc_name[:-len(suf)])
        return any(_pattern_match(c, k) for c in candidates for k in code)

    stale = [d for d in sorted(docs)
             if d not in _NOT_METRICS and not in_code(d)]
    return undocumented, stale, code


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    undocumented, stale, code = check()
    if "--list" in argv:
        for name, sites in sorted(code.items()):
            print(f"{name}  ({sites[0]})")
        return 0
    rc = 0
    for name in undocumented:
        print(f"UNDOCUMENTED metric {name!r} (registered at "
              f"{', '.join(code[name][:3])}) — add it to "
              "docs/OBSERVABILITY.md")
        rc = 1
    for name in stale:
        print(f"STALE docs mention {name!r} — docs/OBSERVABILITY.md names "
              "a metric nothing in the tree registers")
        rc = 1
    if rc == 0:
        print(f"metrics docs lint OK: {len(code)} registered metric "
              f"name(s), all documented; no stale docs mentions")
    return rc


if __name__ == "__main__":
    sys.exit(main())
