#!/usr/bin/env python
"""Bench artifact contract check: bench.py must print exactly one line of
parseable JSON with the headline metric keys, succeeding (value numeric)
on TPU and degrading to a diagnostic (value null, error set) elsewhere."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main() -> int:
    # budget = bench.py's own hard total wall-clock cap
    # (HVD_BENCH_TOTAL_BUDGET_S, default 1200 s) plus slack: bench must
    # always get to print its failure JSON rather than be killed mid-loop
    budget = float(os.environ.get("HVD_BENCH_TOTAL_BUDGET_S", "1200"))
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, cwd=REPO, timeout=budget + 120)
    except subprocess.TimeoutExpired as e:
        print("bench.py exceeded even the worst-case budget — the "
              "attempt loop itself is wedged (contract violation):\n"
              f"stderr tail: {(e.stderr or '')[-500:]}")
        return 1
    lines = [ln for ln in out.stdout.strip().splitlines() if ln.strip()]
    if len(lines) != 1:
        print(f"expected 1 stdout line, got {len(lines)}:\n{out.stdout}")
        return 1
    doc = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "mfu", "phases"):
        if key not in doc:
            print(f"missing key {key!r} in {doc}")
            return 1
    if doc["value"] is None and "error" not in doc:
        print(f"null value without diagnostic error: {doc}")
        return 1
    # per-phase timing contract: a run that got as far as touching devices
    # must say WHERE the wall clock went — either completed phases
    # (device_init, setup, compile, warmup, measure: cumulative seconds) or
    # at minimum the phase in flight at kill time. A child that died
    # BEFORE its first phase boundary (import crash, unwritable tmpdir)
    # legitimately has neither — there the diagnostic is doc["error"],
    # already required above.
    phases = doc["phases"]
    if not isinstance(phases, dict):
        print(f"'phases' is not a dict: {doc}")
        return 1
    if not any(isinstance(v, (int, float)) for v in phases.values()) \
            and not doc.get("phase_in_progress") \
            and not doc.get("error"):
        print(f"no per-phase timings and no phase_in_progress: {doc}")
        return 1
    known = {"device_init", "setup", "compile", "warmup", "measure"}
    bogus = set(phases) - known
    if bogus:
        print(f"unknown phase names {sorted(bogus)} in {doc}")
        return 1
    print(f"bench contract OK: {doc}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
