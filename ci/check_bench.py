#!/usr/bin/env python
"""Bench artifact contract check: bench.py must print exactly one line of
parseable JSON with the headline metric keys, succeeding (value numeric)
on TPU and degrading to a diagnostic (value null, error set) elsewhere.

``--scaling NEW [--baseline OLD] [--tolerance T]`` is the scaling-curve
regression gate (ISSUE 6): NEW/OLD are MULTICHIP_* artifacts (or raw
dryrun output) whose ``[scaling] {json}`` line carries samples/s vs
world size with and without int8 compression; the gate fails when any
world's throughput (either series) regresses more than T (default 0.25
— CPU-mesh numbers are noisy; the band catches collapses, not jitter)
below the baseline. A baseline without a curve (older rounds) passes
with a note; a NEW artifact without a curve fails — the standing
artifact is the point.

``--tuned TUNED --default DEFAULT [--tolerance T]`` is the
autotune-never-regresses gate (ISSUE 8): TUNED is a scaling artifact
measured with the mesh autotuner on, DEFAULT the same sweep with the
static hand-set config. The gate fails when the tuned plan loses to
the default beyond T at any world (same missing-world evidence rule as
the scaling gate: a world the default measured but the tuned run
didn't is itself a failure) — autotune converging to something WORSE
than the baseline candidate means the search scored garbage, exactly
what must not ship silently.

``--compile-budget NEW [--baseline OLD] [--tolerance T]`` is the
compile-time regression gate (ISSUE 9): the bench doc records
``compile_seconds`` — MEASURED backend-compile time from the compile
hooks (docs/OBSERVABILITY.md "Compile & memory observability"), not
the old wall-clock phase that also timed the first step's run — and
the gate fails when NEW's compile time exceeds the baseline's by more
than T (default 0.5: compile time on shared hosts is noisy; the band
catches a graph-growth or cache-bust regression, not jitter).  A
baseline artifact predating the contract passes with a note (NEW
becomes the baseline); a NEW artifact with a real measured value but
no compile time fails — the recording contract broke.

``--pipeline ARTIFACT`` is the parallelism-plan contract gate
(ISSUE 11): a bench doc produced with ``HVD_BENCH_PP`` > 1 must record
the locked parallelism plan (``parallel_plan``: dp/pp/schedule/
n_microbatches/virtual_stages) and an analytic ``bubble_fraction`` that
MATCHES the schedule's tick-count model
(``horovod_tpu.parallel.pipeline.bubble_fraction``) — a plan/bubble
pair that disagrees means the child measured one layout while
reporting another. ``dp * pp`` must equal ``n_chips``. A doc without
a plan (pp=1 run) passes with a note.  When the doc also carries a
MEASURED bubble (``bubble_measured``, from the pp=1 compute-only
attribution baseline — ISSUE 12) it is range-checked and printed next
to the analytic value with their drift, so analytic-vs-measured
divergence is visible per round without being a gate (remat recompute
and collective latency legitimately live in the gap).

``--serving NEW [--baseline OLD] [--tolerance T]`` is the serving
latency gate (ISSUE 14): NEW/OLD are ``BENCH_SERVE`` artifacts from
``benchmarks/serving_bench.py`` (raw JSON or captured output).  The
gate fails when p99 regresses more than T (default 0.5) over the
baseline's, and — baseline or not — when the artifact is not CLEAN:
``shed_fraction > 0`` (a latency number bought by refusing load is not
a measurement of the same system), failed requests, or a violated
zero-drop audit (unanswered / double-answered ids) all fail.  The
request ledger (ISSUE 19) adds two standalone rules: the artifact must
carry the per-stage decomposition with its books CLOSED
(``stage_unattributed_frac`` under 10% — a p99 whose decomposition no
longer explains it is not actionable), and the reported p99 is
replayed through the shared quantile over the artifact's own
``latency_sample``.

``--serving-gen NEW [--baseline OLD] [--tolerance T]`` is the
generative-throughput gate (ISSUE 17): NEW/OLD are ``BENCH_SERVE_GEN``
artifacts from ``benchmarks/serving_bench.py --generate`` (raw JSON or
captured output).  Baseline or not, the artifact must be CLEAN: zero
failed requests (a tokens/s number that dropped streams is not a
measurement), ``decode_compiles == 1`` (slot churn re-triggering XLA
compilation is the one failure mode the static-slot design exists to
prevent — a second compile IS the regression), and ``speedup > 1``
(continuous batching must beat the request-level gang baseline it
ships next to, measured on the same warm engine with identical
tracing/callback overhead).  With a baseline, ``tokens_per_s`` must
not regress more than T (default 0.5 — CPU decode windows are noisy).
Baselines auto-discover from committed ``BENCH_SERVE_GEN*.json``;
failure artifacts are skipped LOUDLY, same semantics as ``--goodput``.

``--goodput NEW [--baseline OLD] [--tolerance T]`` is the goodput
regression gate (ISSUE 16): the bench doc records ``goodput`` — the
closed-books wall-clock ledger (docs/OBSERVABILITY.md "Goodput
ledger") — and ``mfu_attribution`` (the roofline decomposition of
1-MFU into category shares).  The gate fails when (a) NEW carries a
real measured value but no goodput section (recording contract broke),
(b) NEW's books did not close (the categories failed to sum to wall
time within the ledger's tolerance — the accounting itself is broken),
or (c) the ``exposed_comm`` or ``compile`` share grew more than T
(default 0.1, ABSOLUTE share points — CPU windows are noisy) over the
baseline's.  Baselines auto-discover from committed ``BENCH_r*.json``;
null-valued failure artifacts are skipped LOUDLY (a silent skip reads
as "compared against the last round" when it wasn't).

``--trajectory ARTIFACT [--tolerance T]`` is the within-window drift
gate (ISSUE 7): the bench doc now records ``step_time_series`` — every
iteration of the timing window — so a run whose *mean* looks fine but
whose steps were degrading (thermal creep, a neighbor ramping up, a
leak) fails instead of shipping a number that was only true at the
start of the window.  The gate compares the mean of the window's last
third against its first third; drift beyond T (default 0.5 — window
noise on shared CPUs is large) fails.  The main contract check applies
the same gate automatically when the doc carries a real (non-null)
measured value and enough points."""

import glob
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def extract_scaling_curve(text: str):
    """Last ``[scaling] {json}`` line of a dryrun's output, or None.
    Accepts either raw text or a MULTICHIP artifact's ``tail`` field."""
    doc = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("[scaling] "):
            continue
        try:
            parsed = json.loads(line[len("[scaling] "):])
        except ValueError:
            continue  # progress lines ([scaling] world=...) are not JSON
        if isinstance(parsed, dict) and "scaling_curve" in parsed:
            doc = parsed
    return doc


def _load_curve(path: str):
    with open(path) as f:
        text = f.read()
    try:  # MULTICHIP artifact: the dryrun output lives in "tail"
        artifact = json.loads(text)
        if isinstance(artifact, dict) and "tail" in artifact:
            text = artifact["tail"]
    except ValueError:
        pass  # raw dryrun output
    return extract_scaling_curve(text)


def check_scaling_regression(new: dict, baseline: dict,
                             tolerance: float) -> list:
    """Regressions beyond the band: [(world, series, new, base), ...].
    A baseline world the new curve failed to measure (but could have —
    it fits the new run's device count) is itself a regression: a
    slowdown that eats the measurement budget must not erase the
    evidence and pass (``None`` marks the missing measurement)."""
    base_by_world = {row["world"]: row
                     for row in baseline.get("scaling_curve", [])}
    new_worlds = {row["world"] for row in new.get("scaling_curve", [])}
    bad = []
    for row in new.get("scaling_curve", []):
        base = base_by_world.get(row["world"])
        if base is None:
            continue
        for series in ("samples_per_sec", "samples_per_sec_int8"):
            n, b = row.get(series), base.get(series)
            if n is not None and b and n < b * (1.0 - tolerance):
                bad.append((row["world"], series, n, b))
    new_capacity = new.get("n_devices") or max(new_worlds, default=0)
    for world, base in sorted(base_by_world.items()):
        if world <= new_capacity and world not in new_worlds:
            bad.append((world, "missing", None,
                        base.get("samples_per_sec")))
    return bad


TRAJECTORY_MIN_POINTS = 6


def check_trajectory(series, tolerance: float = 0.5):
    """Within-window drift check over a ``step_time_series`` list.

    Returns None when healthy, else a human-readable failure string.
    Fewer than TRAJECTORY_MIN_POINTS points (contract tests shrink
    HVD_BENCH_ITERS) or non-numeric content is not gated — but a
    *malformed* series (non-list) is always an error: the recording
    contract broke."""
    if not isinstance(series, list):
        return f"step_time_series is not a list: {series!r}"
    vals = [v for v in series if isinstance(v, (int, float)) and v >= 0]
    if len(vals) != len(series):
        return f"step_time_series carries non-numeric entries: {series!r}"
    if len(vals) < TRAJECTORY_MIN_POINTS:
        return None  # too short to judge drift (smoke/contract runs)
    third = max(1, len(vals) // 3)
    head = sum(vals[:third]) / third
    tail = sum(vals[-third:]) / third
    if head > 0 and tail > head * (1.0 + tolerance):
        return (f"trajectory drift: last third of the window averaged "
                f"{tail:.6f}s/step vs {head:.6f}s at the start "
                f"(> {tolerance:.0%} slower over {len(vals)} steps)")
    return None


def _load_bench_doc(path: str):
    """The bench result doc from a raw doc JSON, a BENCH_r* artifact
    (doc under ``parsed``), or a BENCH_MEASURED run entry (under
    ``result``)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict):
        for key in ("parsed", "result"):
            if isinstance(doc.get(key), dict):
                return doc[key]
    return doc if isinstance(doc, dict) else None


def discover_baseline(pattern, exclude, want, what):
    """Newest committed artifact matching ``pattern`` whose doc
    satisfies ``want(doc)``.  Every rejected candidate is reported
    LOUDLY with the reason — a gate that silently skipped a null-valued
    round reads as "compared against the last artifact" when it
    actually reached further back (or found nothing).  ``what`` names
    the gated section for the messages."""
    for path in sorted(glob.glob(os.path.join(REPO, pattern)),
                       reverse=True):
        if os.path.abspath(path) == os.path.abspath(exclude):
            continue
        name = os.path.basename(path)
        try:
            doc = _load_bench_doc(path)
        except (OSError, ValueError) as e:
            print(f"baseline discovery: skipping {name} "
                  f"(unreadable: {e})")
            continue
        if not doc:
            print(f"baseline discovery: skipping {name} "
                  "(no parseable bench doc)")
            continue
        if doc.get("value") is None:
            print(f"baseline discovery: skipping {name} "
                  "(null-valued failure artifact — no measurement to "
                  "compare against)")
            continue
        if not want(doc):
            print(f"baseline discovery: skipping {name} "
                  f"(no {what} recorded)")
            continue
        return path, doc
    return None, None


def doc_compile_seconds(doc):
    """Measured compile seconds with wall-clock fallback for artifacts
    predating the compile-hook contract."""
    if not isinstance(doc, dict):
        return None, None
    v = doc.get("compile_seconds")
    if isinstance(v, (int, float)):
        return float(v), "hooks"
    v = doc.get("compile_s")
    if isinstance(v, (int, float)):
        return float(v), "wall"
    return None, None


def check_compile_budget(new: dict, baseline, tolerance: float):
    """None when within budget, else a failure string."""
    n, n_src = doc_compile_seconds(new)
    if n is None:
        if new.get("value") is None:
            return None  # a failure doc has no compile to judge
        return ("new artifact carries a measured value but no "
                "compile_seconds/compile_s — the recording contract "
                "broke")
    b, b_src = doc_compile_seconds(baseline) if baseline else (None, None)
    if b is None:
        return None  # no baseline: NEW becomes it
    if b > 0 and n > b * (1.0 + tolerance):
        return (f"compile-time regression: {n:.1f}s ({n_src}) vs "
                f"baseline {b:.1f}s ({b_src}) — more than "
                f"{tolerance:.0%} over budget")
    return None


def compile_budget_main(argv) -> int:
    new_path = argv[argv.index("--compile-budget") + 1]
    tolerance = float(argv[argv.index("--tolerance") + 1]) \
        if "--tolerance" in argv else 0.5
    new = _load_bench_doc(new_path)
    if not new:
        print(f"no bench doc in {new_path}")
        return 1
    baseline = None
    base_path = None
    if "--baseline" in argv:
        base_path = argv[argv.index("--baseline") + 1]
        baseline = _load_bench_doc(base_path)
    else:
        # newest committed BENCH_r*.json carrying a compile time
        base_path, baseline = discover_baseline(
            "BENCH_r*.json", new_path,
            lambda d: doc_compile_seconds(d)[0] is not None,
            what="compile time")
    problem = check_compile_budget(new, baseline, tolerance)
    if problem:
        print(f"compile-budget gate FAILED for {new_path}: {problem}")
        return 1
    n, src = doc_compile_seconds(new)
    if n is None:
        # a failure doc (value null) passes the gate with nothing to
        # format — don't let the success print crash on None
        print(f"compile-budget gate: {new_path} is a failure artifact "
              "with no compile time; nothing to judge")
    elif baseline is None or doc_compile_seconds(baseline)[0] is None:
        print(f"compile-budget gate: no baseline compile time "
              f"({base_path}); accepting "
              f"{'%.1fs' % n if n is not None else 'n/a'} as the new "
              "baseline")
    else:
        b, bsrc = doc_compile_seconds(baseline)
        print(f"compile-budget gate OK vs {base_path} "
              f"(tolerance {tolerance:.0%}): {n:.1f}s ({src}) vs "
              f"{b:.1f}s ({bsrc})")
    return 0


# the shares the goodput gate holds against the baseline: the two
# costs an engineering change most plausibly regresses silently (an
# overlap-schedule break shows up as exposed_comm; a graph-growth or
# cache-bust regression as compile)
GOODPUT_GATED_CATEGORIES = ("exposed_comm", "compile")


def doc_goodput(doc):
    """The goodput ledger section of a bench doc, or None."""
    if not isinstance(doc, dict):
        return None
    gp = doc.get("goodput")
    return gp if isinstance(gp, dict) else None


def check_goodput(new: dict, baseline, tolerance: float) -> list:
    """Problems with an artifact's goodput books: list of failure
    strings (empty = gate passes).

    Three rules (ISSUE 16): (1) a real-valued artifact must CARRY the
    ledger — a measured number whose wall-clock account is missing is a
    recording-contract break; (2) the books must CLOSE — categories
    summing to wall time within the ledger's own tolerance is the whole
    point, and an artifact that failed its double-entry check is
    evidence of broken accounting, not a perf number; (3) the
    ``exposed_comm`` and ``compile`` shares must not grow more than
    ``tolerance`` ABSOLUTE share points over the baseline's."""
    gp = doc_goodput(new)
    if gp is None:
        if new.get("value") is None:
            return []  # a failure doc has no window to account
        return ["new artifact carries a measured value but no goodput "
                "section — the recording contract broke"]
    problems = []
    if not gp.get("closed", False) or gp.get("books_violations"):
        problems.append(
            f"goodput books did NOT close: residual {gp.get('residual_s')}s "
            f"over {gp.get('wall_s')}s wall "
            f"({gp.get('books_violations', 0)} violating window(s), "
            f"ledger tolerance {gp.get('tolerance')}) — the accounting "
            "is broken, not just slow")
    fr = gp.get("fractions") or {}
    base_gp = doc_goodput(baseline) if baseline else None
    if base_gp:
        base_fr = base_gp.get("fractions") or {}
        for cat in GOODPUT_GATED_CATEGORIES:
            n, b = fr.get(cat), base_fr.get(cat)
            if isinstance(n, (int, float)) and isinstance(b, (int, float)) \
                    and n > b + tolerance:
                problems.append(
                    f"{cat} share REGRESSION: {n:.1%} of wall time vs "
                    f"baseline {b:.1%} (> {tolerance:.0%} absolute "
                    "growth)")
    return problems


def goodput_main(argv) -> int:
    new_path = argv[argv.index("--goodput") + 1]
    tolerance = float(argv[argv.index("--tolerance") + 1]) \
        if "--tolerance" in argv else 0.1
    new = _load_bench_doc(new_path)
    if not new:
        print(f"no bench doc in {new_path}")
        return 1
    baseline = None
    base_path = None
    if "--baseline" in argv:
        base_path = argv[argv.index("--baseline") + 1]
        baseline = _load_bench_doc(base_path)
        if baseline and doc_goodput(baseline) is None:
            print(f"baseline {base_path} predates the goodput contract; "
                  "judging the new artifact standalone")
    else:
        base_path, baseline = discover_baseline(
            "BENCH_r*.json", new_path,
            lambda d: doc_goodput(d) is not None,
            what="goodput section")
    problems = check_goodput(new, baseline, tolerance)
    if problems:
        for p in problems:
            print(f"goodput gate FAILED for {new_path}: {p}")
        return 1
    gp = doc_goodput(new)
    if gp is None:
        print(f"goodput gate: {new_path} is a failure artifact with no "
              "window to account; nothing to judge")
        return 0
    att = new.get("mfu_attribution") or {}
    note = f" vs {base_path}" if baseline and doc_goodput(baseline) \
        else " (no baseline: standalone books check only)"
    mfu = att.get("mfu")
    print(f"goodput gate OK{note} (tolerance {tolerance:.0%}): "
          f"productive={gp.get('fraction')} over {gp.get('wall_s')}s / "
          f"{gp.get('windows')} window(s), "
          f"dominating_loss={att.get('dominating')}, "
          f"mfu={'n/a' if mfu is None else mfu}, "
          f"kernel_inefficiency="
          f"{'n/a' if att.get('kernel_inefficiency') is None else att['kernel_inefficiency']}")
    return 0


def check_pipeline_plan(doc: dict):
    """None when the parallel_plan/bubble_fraction pair is coherent,
    else a failure string — NEVER an exception: a corrupt artifact must
    fail the gate with a message, not kill it with a traceback. Docs
    without a plan are not judged here."""
    plan = doc.get("parallel_plan")
    if plan is None:
        return None
    if not isinstance(plan, dict):
        return f"parallel_plan is not an object: {plan!r}"
    for key in ("dp", "pp", "schedule", "n_microbatches"):
        if key not in plan:
            return f"parallel_plan missing key {key!r}: {plan}"
    try:
        dp, pp = int(plan["dp"]), int(plan["pp"])
        n_micro = int(plan["n_microbatches"])
        v = int(plan.get("virtual_stages", 1))
        bubble = float(doc["bubble_fraction"]) \
            if doc.get("bubble_fraction") is not None else None
    except (TypeError, ValueError) as e:
        return f"parallel_plan carries non-numeric fields ({e}): {plan}"
    schedule = str(plan["schedule"])
    if schedule not in ("gpipe", "1f1b", "interleaved"):
        return f"unknown schedule {schedule!r} in parallel_plan"
    if not (1 <= dp and 1 <= pp and 1 <= v):
        return f"non-positive plan dimensions: {plan}"
    if not (1 <= n_micro <= 65536):
        # also bounds the pure-Python interleaved table build below — a
        # corrupt huge M must not hang the gate for minutes
        return f"implausible n_microbatches {n_micro} in parallel_plan"
    if bubble is None:
        return "parallel_plan recorded without bubble_fraction"
    if not (0.0 <= bubble < 1.0):
        return f"bubble_fraction {bubble} outside [0, 1)"
    n_chips = doc.get("n_chips")
    if n_chips and dp * pp != int(n_chips):
        return (f"plan dp*pp = {dp}*{pp} does not tile "
                f"n_chips={n_chips}")
    sys.path.insert(0, REPO)
    try:
        from horovod_tpu.parallel.pipeline import bubble_fraction
        expect = bubble_fraction(schedule, pp, n_micro, v)
    except Exception as e:
        return f"analytic bubble model rejected {plan}: {e}"
    finally:
        sys.path.remove(REPO)
    if abs(bubble - expect) > 5e-4:
        return (f"recorded bubble_fraction {bubble} disagrees with the "
                f"analytic value {expect:.4f} for {plan} — the child "
                "measured one layout while reporting another")
    measured = doc.get("bubble_measured")
    if measured is not None:
        # the MEASURED bubble (compute-only attribution) is judged for
        # plausibility only — drift vs the analytic value is expected
        # (remat recompute, collective latency) and PRINTED, not gated
        try:
            measured = float(measured)
        except (TypeError, ValueError):
            return f"bubble_measured is not a number: {measured!r}"
        if not (0.0 <= measured < 1.0):
            return f"bubble_measured {measured} outside [0, 1)"
    return None


def pipeline_main(argv) -> int:
    path = argv[argv.index("--pipeline") + 1]
    doc = _load_bench_doc(path)
    if not doc:
        print(f"no bench doc in {path}")
        return 1
    problem = check_pipeline_plan(doc)
    if problem:
        print(f"pipeline gate FAILED for {path}: {problem}")
        return 1
    plan = doc.get("parallel_plan")
    if plan is None:
        print(f"pipeline gate: {path} carries no parallel_plan "
              "(pp=1 run); nothing to judge")
    else:
        measured = doc.get("bubble_measured")
        analytic = doc["bubble_fraction"]
        # analytic AND measured, plus their drift, every round: the
        # analytic value is the tick model, the measured one is what
        # the devices actually did (remat + comm land in the gap)
        if measured is not None:
            detail = (f" bubble_analytic={analytic} "
                      f"bubble_measured={measured} "
                      f"drift={round(float(measured) - float(analytic), 4)}")
        else:
            detail = (f" bubble_analytic={analytic} "
                      "bubble_measured=n/a (no compute-only baseline "
                      "in this artifact)")
        print(f"pipeline gate OK for {path}: dp{plan['dp']} x "
              f"pp{plan['pp']} {plan['schedule']} "
              f"m{plan['n_microbatches']} v{plan.get('virtual_stages', 1)}"
              + detail)
    return 0


def trajectory_main(argv) -> int:
    path = argv[argv.index("--trajectory") + 1]
    tolerance = float(argv[argv.index("--tolerance") + 1]) \
        if "--tolerance" in argv else 0.5
    with open(path) as f:
        doc = json.load(f)
    series = doc.get("step_time_series")
    if series is None:
        print(f"no step_time_series in {path}: the artifact predates the "
              "trajectory contract (or the child died before the timing "
              "window)")
        return 1
    problem = check_trajectory(series, tolerance)
    if problem:
        print(f"trajectory gate FAILED for {path}: {problem}")
        return 1
    print(f"trajectory gate OK for {path} ({len(series)} steps, "
          f"tolerance {tolerance:.0%})")
    return 0


def tuned_main(argv) -> int:
    """``--tuned TUNED --default DEFAULT``: the tuned run must not lose
    to the static default. The comparison IS the scaling-regression
    check with the default as baseline — a tuned curve below the
    default's band, or a world the tuned run failed to measure, fails."""
    tuned_path = argv[argv.index("--tuned") + 1]
    if "--default" not in argv:
        print("--tuned requires --default DEFAULT_ARTIFACT (the "
              "static-config run to hold the tuned run against)")
        return 2
    default_path = argv[argv.index("--default") + 1]
    tolerance = float(argv[argv.index("--tolerance") + 1]) \
        if "--tolerance" in argv else 0.25
    tuned = _load_curve(tuned_path)
    default = _load_curve(default_path)
    if not tuned or not tuned.get("scaling_curve"):
        print(f"no scaling curve in tuned artifact {tuned_path}")
        return 1
    if not default or not default.get("scaling_curve"):
        print(f"no scaling curve in default artifact {default_path}; "
              "cannot judge the tuned run — measure the static config "
              "first")
        return 1
    bad = check_scaling_regression(tuned, default, tolerance)
    if bad:
        for world, series, n, b in bad:
            if n is None:
                print(f"tuned-vs-default FAILED world={world}: default "
                      f"measured {b:.2f}/s but the tuned run has no "
                      "measurement")
            else:
                print(f"tuned-vs-default FAILED world={world} {series}: "
                      f"tuned {n:.2f}/s vs default {b:.2f}/s "
                      f"(> {tolerance:.0%} below — autotune regressed a "
                      "previously good config)")
        return 1
    print(f"tuned-vs-default OK (tolerance {tolerance:.0%}): "
          + "; ".join(f"w{r['world']}={r['samples_per_sec']}/s"
                      for r in tuned["scaling_curve"]))
    return 0


def _default_baseline(exclude: str):
    """Newest committed MULTICHIP_r*.json that carries a curve."""
    for path in sorted(glob.glob(os.path.join(REPO, "MULTICHIP_r*.json")),
                       reverse=True):
        if os.path.abspath(path) == os.path.abspath(exclude):
            continue
        curve = _load_curve(path)
        if curve:
            return path, curve
    return None, None


def scaling_main(argv) -> int:
    new_path = argv[argv.index("--scaling") + 1]
    tolerance = float(argv[argv.index("--tolerance") + 1]) \
        if "--tolerance" in argv else 0.25
    new = _load_curve(new_path)
    if not new or not new.get("scaling_curve"):
        print(f"no scaling curve in {new_path}: the dryrun must emit the "
              "[scaling] line (HVD_DRYRUN_SCALING=0 set, or the child "
              "died before the scaling phase?)")
        return 1
    if "--baseline" in argv:
        base_path = argv[argv.index("--baseline") + 1]
        base = _load_curve(base_path)
    else:
        base_path, base = _default_baseline(new_path)
    if not base:
        print(f"scaling gate: no baseline curve available ({base_path}); "
              f"accepting {len(new['scaling_curve'])}-point curve as the "
              "new baseline")
        return 0
    bad = check_scaling_regression(new, base, tolerance)
    if new.get("truncated"):
        # a budget-truncated curve means the measurement itself slowed
        # down — exactly the condition a perf gate must not wave through
        print("scaling gate: NEW curve is truncated (the dryrun's "
              "scaling budget ran out) — investigate the slowdown")
        return 1
    if bad:
        for world, series, n, b in bad:
            if n is None:
                print(f"scaling REGRESSION world={world}: present in "
                      f"baseline ({b:.2f}/s) but NOT measured this run")
            else:
                print(f"scaling REGRESSION world={world} {series}: "
                      f"{n:.2f}/s vs baseline {b:.2f}/s "
                      f"(> {tolerance:.0%} below)")
        return 1
    print(f"scaling gate OK vs {base_path} "
          f"(tolerance {tolerance:.0%}): "
          + "; ".join(f"w{r['world']}={r['samples_per_sec']}/s"
                      for r in new["scaling_curve"]))
    return 0


def _load_serving_doc(path: str):
    """A serving artifact: raw JSON, or the last ``BENCH_SERVE {json}``
    line of captured bench output."""
    with open(path) as f:
        text = f.read()
    doc = None
    try:
        parsed = json.loads(text)
        if isinstance(parsed, dict) and parsed.get("bench") == "serving":
            doc = parsed
    except ValueError:
        pass
    if doc is None:
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("BENCH_SERVE "):
                try:
                    parsed = json.loads(line[len("BENCH_SERVE "):])
                except ValueError:
                    continue
                if isinstance(parsed, dict):
                    doc = parsed
    return doc


#: the books-close bar (ISSUE 19): the stage decomposition must explain
#: at least 90% of the latency it rides with — past this, the ledger is
#: no longer measuring where the time went
SERVING_UNATTRIBUTED_MAX = 0.10


def check_serving(new: dict, baseline, tolerance: float):
    """Problems with a serving artifact: list of failure strings.

    Rules (ISSUE 14 + the request ledger, ISSUE 19): (1) a "clean"
    latency number that SHED requests is not clean — load-shedding
    trades completeness for latency, so a p99 bought that way must not
    pass as a measurement of the same system; same for failed/
    unanswered/double-answered requests (the zero-drop audit rides the
    artifact).  (2) p99 must not regress more than ``tolerance`` over
    the baseline's.  (3) the BOOKS must CLOSE: a measured artifact
    carries the per-stage decomposition
    (``stage_seconds``/``stage_unattributed_frac``) and its
    unattributed residual stays under
    :data:`SERVING_UNATTRIBUTED_MAX` — a p99 whose decomposition no
    longer explains it is a number nobody can act on.  (4) when the
    artifact ships its ``latency_sample``, the reported p99 is REPLAYED
    through the shared quantile implementation
    (:func:`horovod_tpu.serving.ledger.quantile`) — the gate checks the
    math, not just the number (wide band: the sample is strided)."""
    problems = []
    if not new.get("requests"):
        problems.append("no requests measured (empty window)")
    if new.get("shed_fraction"):
        problems.append(
            f"shed_fraction={new['shed_fraction']} > 0: the latency "
            "number was bought by shedding load — not a clean number "
            "(lower the client count or raise the admission budget)")
    if new.get("failed"):
        problems.append(f"{new['failed']} request(s) FAILED during the "
                        "measurement window")
    if new.get("unanswered") or new.get("answered_twice"):
        problems.append(
            f"zero-drop audit violated: unanswered="
            f"{new.get('unanswered')} answered_twice="
            f"{new.get('answered_twice')}")
    stages = new.get("stage_seconds")
    unattr = new.get("stage_unattributed_frac")
    if not isinstance(stages, dict) or not stages:
        if new.get("requests"):
            problems.append(
                "no stage_seconds breakdown: the request ledger's "
                "books are missing — the recording contract broke "
                "(rerun with a current benchmarks/serving_bench.py)")
    elif not isinstance(unattr, (int, float)):
        problems.append(
            "stage_seconds present but stage_unattributed_frac is "
            "missing — the books-close evidence did not ride the "
            "artifact")
    elif unattr >= SERVING_UNATTRIBUTED_MAX:
        problems.append(
            f"request-ledger books did NOT close: "
            f"{unattr:.1%} of attributed wall-clock is unattributed "
            f"(>= {SERVING_UNATTRIBUTED_MAX:.0%}) — the stage "
            f"decomposition no longer explains the p99 it ships with "
            f"(dominant stage: {new.get('dominant_stage')})")
    sample = new.get("latency_sample")
    if isinstance(sample, list) and len(sample) >= 10 \
            and new.get("p99_s"):
        sys.path.insert(0, REPO)
        try:
            from horovod_tpu.serving.ledger import quantile
            replay = quantile(sorted(float(v) for v in sample), 0.99)
        except Exception as e:
            replay = None
            problems.append(f"latency_sample replay failed: {e!r}")
        finally:
            sys.path.remove(REPO)
        if replay is not None:
            # the band is generous (strided sample + absolute floor):
            # this catches a percentile implementation drifting, not
            # sampling noise
            band = max(new["p99_s"] * 0.5, 0.002)
            if abs(replay - new["p99_s"]) > band:
                problems.append(
                    f"p99 replay mismatch: artifact says "
                    f"{new['p99_s']:.6f}s but the shared quantile over "
                    f"its own latency_sample says {replay:.6f}s — the "
                    "percentile math diverged")
    if baseline and baseline.get("p99_s") and new.get("p99_s"):
        base_p99, new_p99 = baseline["p99_s"], new["p99_s"]
        if new_p99 > base_p99 * (1.0 + tolerance):
            problems.append(
                f"p99 REGRESSION: {new_p99:.6f}s vs baseline "
                f"{base_p99:.6f}s (> {tolerance:.0%} above)")
    return problems


def serving_main(argv) -> int:
    new_path = argv[argv.index("--serving") + 1]
    tolerance = float(argv[argv.index("--tolerance") + 1]) \
        if "--tolerance" in argv else 0.5
    new = _load_serving_doc(new_path)
    if not new:
        print(f"no serving artifact in {new_path}: run "
              "benchmarks/serving_bench.py --out first")
        return 1
    baseline = None
    base_path = None
    if "--baseline" in argv:
        base_path = argv[argv.index("--baseline") + 1]
        baseline = _load_serving_doc(base_path)
        if not baseline:
            print(f"baseline {base_path} carries no serving artifact; "
                  "judging the new run standalone")
    problems = check_serving(new, baseline, tolerance)
    if problems:
        for p in problems:
            print(f"serving gate FAILED for {new_path}: {p}")
        return 1
    note = f" vs {base_path}" if baseline else \
        " (no baseline: standalone checks only)"
    print(f"serving gate OK{note}: qps={new.get('qps')} "
          f"p50={new.get('p50_s')}s p99={new.get('p99_s')}s "
          f"shed_fraction={new.get('shed_fraction')} "
          f"dominant_stage={new.get('dominant_stage')} "
          f"unattributed={new.get('stage_unattributed_frac')} over "
          f"{new.get('requests')} requests")
    return 0


def _load_serving_gen_doc(path: str):
    """A generate-bench artifact: raw JSON, or the last
    ``BENCH_SERVE_GEN {json}`` line of captured bench output.  The
    space-suffixed prefix keeps ``BENCH_SERVE `` lines (request-level
    serving artifacts) from matching."""
    with open(path) as f:
        text = f.read()
    doc = None
    try:
        parsed = json.loads(text)
        if isinstance(parsed, dict) and \
                parsed.get("bench") == "serving_generate":
            doc = parsed
    except ValueError:
        pass
    if doc is None:
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("BENCH_SERVE_GEN "):
                try:
                    parsed = json.loads(line[len("BENCH_SERVE_GEN "):])
                except ValueError:
                    continue
                if isinstance(parsed, dict):
                    doc = parsed
    return doc


def check_serving_gen(new: dict, baseline, tolerance: float):
    """Problems with a generate-bench artifact: list of failure strings.

    Three standalone rules (ISSUE 17) plus a baseline rule: (1) zero
    failed requests — a tokens/s bought by dropping streams is not a
    measurement of the same system; (2) ``decode_compiles`` must be
    EXACTLY 1 — the static-slot engine's whole contract is that slot
    churn never changes the compiled shape, so a second compile is the
    regression this gate exists to catch (and 0 means the compile
    counter broke — also not a pass); (3) ``speedup > 1`` — the
    continuous engine must beat the request-level gang baseline
    measured alongside it on the same warm engine; (4) with a
    baseline, ``tokens_per_s`` must not fall more than ``tolerance``
    below the baseline's."""
    problems = []
    if not new.get("requests"):
        problems.append("no requests measured (empty window)")
    if new.get("failed"):
        problems.append(
            f"{new['failed']} request(s) FAILED (finish_reason != "
            "'length') during the measurement window")
    compiles = new.get("decode_compiles")
    if compiles != 1:
        problems.append(
            f"decode_compiles={compiles}, expected exactly 1: the "
            "static-slot contract is one compile regardless of churn "
            "(0 means the compile counter itself broke)")
    speedup = new.get("speedup")
    if not isinstance(speedup, (int, float)) or speedup <= 1.0:
        problems.append(
            f"speedup={speedup}: continuous batching must beat the "
            "request-level gang baseline measured on the same engine")
    if baseline and baseline.get("tokens_per_s") \
            and new.get("tokens_per_s"):
        base_tps, new_tps = baseline["tokens_per_s"], new["tokens_per_s"]
        if new_tps < base_tps * (1.0 - tolerance):
            problems.append(
                f"tokens/s REGRESSION: {new_tps:.2f} vs baseline "
                f"{base_tps:.2f} (> {tolerance:.0%} below)")
    return problems


def serving_gen_main(argv) -> int:
    new_path = argv[argv.index("--serving-gen") + 1]
    tolerance = float(argv[argv.index("--tolerance") + 1]) \
        if "--tolerance" in argv else 0.5
    new = _load_serving_gen_doc(new_path)
    if not new:
        print(f"no generate artifact in {new_path}: run "
              "benchmarks/serving_bench.py --generate first")
        return 1
    baseline = None
    base_path = None
    if "--baseline" in argv:
        base_path = argv[argv.index("--baseline") + 1]
        baseline = _load_serving_gen_doc(base_path)
        if not baseline:
            print(f"baseline {base_path} carries no generate artifact; "
                  "judging the new run standalone")
    else:
        # Gen docs carry no "value" key, so discover_baseline (which
        # requires one) cannot be reused — mirror its loud-skip
        # semantics over the gen artifact pattern instead.
        for path in sorted(
                glob.glob(os.path.join(REPO, "BENCH_SERVE_GEN*.json")),
                reverse=True):
            if os.path.abspath(path) == os.path.abspath(new_path):
                continue
            name = os.path.basename(path)
            try:
                doc = _load_serving_gen_doc(path)
            except (OSError, ValueError) as e:
                print(f"baseline discovery: skipping {name} "
                      f"(unreadable: {e})")
                continue
            if not doc:
                print(f"baseline discovery: skipping {name} "
                      "(no parseable generate artifact)")
                continue
            if not doc.get("tokens_per_s"):
                print(f"baseline discovery: skipping {name} "
                      "(null tokens/s — a failure artifact has no "
                      "measurement to compare against)")
                continue
            base_path, baseline = path, doc
            break
    problems = check_serving_gen(new, baseline, tolerance)
    if problems:
        for p in problems:
            print(f"serving-gen gate FAILED for {new_path}: {p}")
        return 1
    note = f" vs {base_path}" if baseline else \
        " (no baseline: standalone checks only)"
    print(f"serving-gen gate OK{note}: "
          f"tokens_per_s={new.get('tokens_per_s')} "
          f"speedup={new.get('speedup')}x "
          f"ttft_p99={new.get('ttft_p99_s')}s "
          f"itl_p99={new.get('itl_p99_s')}s "
          f"occupancy={new.get('slot_occupancy_mean')} "
          f"compiles={new.get('decode_compiles')} over "
          f"{new.get('requests')} requests")
    return 0


def _load_rollout_doc(path: str):
    """A rollout-bench artifact: raw JSON, or the last
    ``BENCH_ROLLOUT {json}`` line of captured bench output."""
    with open(path) as f:
        text = f.read()
    doc = None
    try:
        parsed = json.loads(text)
        if isinstance(parsed, dict) and parsed.get("bench") == "rollout":
            doc = parsed
    except ValueError:
        pass
    if doc is None:
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("BENCH_ROLLOUT "):
                try:
                    parsed = json.loads(line[len("BENCH_ROLLOUT "):])
                except ValueError:
                    continue
                if isinstance(parsed, dict):
                    doc = parsed
    return doc


def check_rollout(new: dict, baseline, tolerance: float):
    """Problems with a rollout-bench artifact: list of failure strings.

    Standalone rules (ISSUE 18): (1) traffic was actually served
    during the rollout; (2) the zero-drop assertion — zero failed,
    zero unanswered, zero answered-twice across BOTH governed
    transitions (pin → rollback repin, pin → promote): a rollout that
    dropped a request is not 'governed'; (3) both transition latencies
    were measured (a null promote_s/rollback_s is a failure artifact,
    not a pass).  Baseline rule: neither latency may regress more than
    ``tolerance`` above the baseline's."""
    problems = []
    if not new.get("requests"):
        problems.append("no requests measured during the rollout")
    for key in ("failed", "unanswered", "answered_twice"):
        if new.get(key):
            problems.append(
                f"{key}={new[key]}: the rollout dropped/duplicated "
                "requests — the zero-drop assertion failed")
    for key in ("promote_s", "rollback_s"):
        v = new.get(key)
        if not isinstance(v, (int, float)) or v <= 0:
            problems.append(
                f"{key}={v}: transition latency was not measured "
                "(a failure artifact has no measurement)")
        elif baseline and isinstance(baseline.get(key), (int, float)) \
                and baseline[key] > 0 \
                and v > baseline[key] * (1.0 + tolerance):
            problems.append(
                f"{key} REGRESSION: {v:.3f}s vs baseline "
                f"{baseline[key]:.3f}s (> {tolerance:.0%} above)")
    return problems


def rollout_main(argv) -> int:
    new_path = argv[argv.index("--rollout") + 1]
    tolerance = float(argv[argv.index("--tolerance") + 1]) \
        if "--tolerance" in argv else 0.5
    new = _load_rollout_doc(new_path)
    if not new:
        print(f"no rollout artifact in {new_path}: run "
              "benchmarks/rollout_bench.py first")
        return 1
    baseline = None
    base_path = None
    if "--baseline" in argv:
        base_path = argv[argv.index("--baseline") + 1]
        baseline = _load_rollout_doc(base_path)
        if not baseline:
            print(f"baseline {base_path} carries no rollout artifact; "
                  "judging the new run standalone")
    else:
        # same loud-skip discovery convention as the serving-gen gate:
        # a skipped baseline must SAY why, and a failure artifact
        # (null latency) is never silently compared against
        for path in sorted(
                glob.glob(os.path.join(REPO, "BENCH_ROLLOUT*.json")),
                reverse=True):
            if os.path.abspath(path) == os.path.abspath(new_path):
                continue
            name = os.path.basename(path)
            try:
                doc = _load_rollout_doc(path)
            except (OSError, ValueError) as e:
                print(f"baseline discovery: skipping {name} "
                      f"(unreadable: {e})")
                continue
            if not doc:
                print(f"baseline discovery: skipping {name} "
                      "(no parseable rollout artifact)")
                continue
            if not doc.get("promote_s") or not doc.get("rollback_s"):
                print(f"baseline discovery: skipping {name} "
                      "(null transition latency — a failure artifact "
                      "has no measurement to compare against)")
                continue
            base_path, baseline = path, doc
            break
    problems = check_rollout(new, baseline, tolerance)
    if problems:
        for p in problems:
            print(f"rollout gate FAILED for {new_path}: {p}")
        return 1
    note = f" vs {base_path}" if baseline else \
        " (no baseline: standalone checks only)"
    print(f"rollout gate OK{note}: promote_s={new.get('promote_s')} "
          f"rollback_s={new.get('rollback_s')} zero-drop over "
          f"{new.get('requests')} requests")
    return 0


def main() -> int:
    # budget = bench.py's own hard total wall-clock cap
    # (HVD_BENCH_TOTAL_BUDGET_S, default 1200 s) plus slack: bench must
    # always get to print its failure JSON rather than be killed mid-loop
    budget = float(os.environ.get("HVD_BENCH_TOTAL_BUDGET_S", "1200"))
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, cwd=REPO, timeout=budget + 120)
    except subprocess.TimeoutExpired as e:
        print("bench.py exceeded even the worst-case budget — the "
              "attempt loop itself is wedged (contract violation):\n"
              f"stderr tail: {(e.stderr or '')[-500:]}")
        return 1
    lines = [ln for ln in out.stdout.strip().splitlines() if ln.strip()]
    if len(lines) != 1:
        print(f"expected 1 stdout line, got {len(lines)}:\n{out.stdout}")
        return 1
    doc = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline", "mfu", "phases"):
        if key not in doc:
            print(f"missing key {key!r} in {doc}")
            return 1
    if doc["value"] is None and "error" not in doc:
        print(f"null value without diagnostic error: {doc}")
        return 1
    # per-phase timing contract: a run that got as far as touching devices
    # must say WHERE the wall clock went — either completed phases
    # (device_init, setup, compile, warmup, measure: cumulative seconds) or
    # at minimum the phase in flight at kill time. A child that died
    # BEFORE its first phase boundary (import crash, unwritable tmpdir)
    # legitimately has neither — there the diagnostic is doc["error"],
    # already required above.
    phases = doc["phases"]
    if not isinstance(phases, dict):
        print(f"'phases' is not a dict: {doc}")
        return 1
    if not any(isinstance(v, (int, float)) for v in phases.values()) \
            and not doc.get("phase_in_progress") \
            and not doc.get("error"):
        print(f"no per-phase timings and no phase_in_progress: {doc}")
        return 1
    known = {"device_init", "setup", "compile", "warmup", "measure"}
    bogus = set(phases) - known
    if bogus:
        print(f"unknown phase names {sorted(bogus)} in {doc}")
        return 1
    # trajectory contract: a doc with a REAL measured value must carry
    # a healthy within-window series (provisional/salvaged docs — the
    # deadline-kill path — legitimately have none).  The automatic gate
    # uses a wide band (default 1.0 = only 2x+ in-window collapses;
    # HVD_BENCH_TRAJECTORY_TOL overrides) — shared-CPU smoke windows
    # are noisy; the strict default lives in the explicit --trajectory
    # mode used for regression analysis
    if doc["value"] is not None and not doc.get("provisional"):
        series = doc.get("step_time_series")
        if series is not None:
            tol = float(os.environ.get("HVD_BENCH_TRAJECTORY_TOL", "1.0"))
            problem = check_trajectory(series, tolerance=tol)
            if problem:
                print(f"bench {problem}")
                return 1
    # parallelism-plan contract (ISSUE 11): a doc that names a plan must
    # name it coherently (automatic form of the --pipeline gate)
    problem = check_pipeline_plan(doc)
    if problem:
        print(f"bench {problem}")
        return 1
    # integrity contract (ISSUE 13): a run whose numeric guardrail
    # skipped steps did LESS optimizer work per measured "step" — its
    # throughput number is not comparable to a clean run and must not
    # pass as one (the skips themselves point at a data-plane problem
    # on the bench host)
    if doc["value"] is not None and doc.get("guard_skipped_steps"):
        print(f"bench run skipped {doc['guard_skipped_steps']} step(s) "
              f"under the numeric guardrail — not a clean perf number: "
              f"{doc}")
        return 1
    if doc["value"] is not None and doc.get("tracing_enabled") \
            and os.environ.get("HVD_BENCH_ALLOW_TRACING", "") != "1":
        print("bench run measured with causal tracing ENABLED "
              "(HVD_TPU_TRACE) — the standing perf number must not "
              "silently pay the tracing overhead; rerun with tracing "
              f"off or set HVD_BENCH_ALLOW_TRACING=1: {doc}")
        return 1
    print(f"bench contract OK: {doc}")
    return 0


if __name__ == "__main__":
    if "--compile-budget" in sys.argv:
        sys.exit(compile_budget_main(sys.argv))
    if "--tuned" in sys.argv:
        sys.exit(tuned_main(sys.argv))
    if "--scaling" in sys.argv:
        sys.exit(scaling_main(sys.argv))
    if "--goodput" in sys.argv:
        sys.exit(goodput_main(sys.argv))
    if "--trajectory" in sys.argv:
        sys.exit(trajectory_main(sys.argv))
    if "--pipeline" in sys.argv:
        sys.exit(pipeline_main(sys.argv))
    if "--rollout" in sys.argv:
        sys.exit(rollout_main(sys.argv))
    if "--serving-gen" in sys.argv:
        sys.exit(serving_gen_main(sys.argv))
    if "--serving" in sys.argv:
        sys.exit(serving_main(sys.argv))
    sys.exit(main())
