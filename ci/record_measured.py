#!/usr/bin/env python
"""Merge capture results into BENCH_MEASURED.json.

``ci/capture_round.sh`` appends verbatim bench.py result lines to a
jsonl file; this tool folds them into the committed measurement log
(provenance for rounds where the driver's own capture window hits a
relay outage — value stays with the measured_at timestamp, never
replacing the driver artifacts).

Usage: python ci/record_measured.py /tmp/round4_captures.jsonl
"""

import datetime
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "BENCH_MEASURED.json")


def main() -> int:
    if len(sys.argv) != 2:
        print(__doc__)
        return 2
    with open(LOG) as f:
        log = json.load(f)
    known = {json.dumps(r["result"], sort_keys=True)
             for r in log.get("runs", [])}
    added = 0
    with open(sys.argv[1]) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            doc = json.loads(line)
            if "result" in doc and "measured_at" in doc:
                # capture_round.sh wraps lines with the CAPTURE time —
                # provenance must not shift to the (possibly much later)
                # merge time, or bench.py's last_measured picks stale data
                measured_at, result = doc["measured_at"], doc["result"]
            else:  # bare bench.py line: merge time is all we have
                measured_at = datetime.datetime.now(
                    datetime.timezone.utc).strftime("%Y-%m-%dT%H:%MZ")
                result = doc
            if not result.get("value"):
                continue  # diagnostic-only lines are not measurements
            key = json.dumps(result, sort_keys=True)
            if key in known:
                continue
            log.setdefault("runs", []).append(
                {"measured_at": measured_at, "result": result})
            known.add(key)
            added += 1
    # keep the committed log's one-line-per-measurement format (its
    # comment documents entries as verbatim bench.py lines)
    body = ",\n".join(
        '    {"measured_at": %s,\n     "result": %s}' % (
            json.dumps(r["measured_at"]), json.dumps(r["result"]))
        for r in log.get("runs", []))
    with open(LOG, "w") as f:
        f.write('{\n  "comment": %s,\n  "runs": [\n%s\n  ]\n}\n'
                % (json.dumps(log.get("comment", "")), body))
    print(f"recorded {added} new measurement(s) into {LOG}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
