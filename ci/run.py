#!/usr/bin/env python
"""CI matrix runner (reference analog: the Buildkite pipeline scripts
driving docker-compose test services). Usage:

    python ci/run.py               # every tier
    python ci/run.py --tier single parallel
    python ci/run.py --list
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_matrix() -> dict:
    with open(os.path.join(REPO, "ci", "matrix.yaml")) as f:
        return yaml.safe_load(f)["tiers"]


def run_tier(name: str, spec: dict) -> bool:
    print(f"=== tier {name}: {spec['description'].strip()}", flush=True)
    timeout = spec.get("timeout_minutes", 30) * 60
    if "setup" in spec:
        rc = subprocess.run(spec["setup"], shell=True, cwd=REPO).returncode
        if rc != 0:
            print(f"--- tier {name}: SETUP FAILED rc={rc}", flush=True)
            return False
    if "command" in spec:
        cmd = spec["command"].split()
    else:
        cmd = [sys.executable, "-m", "pytest", "-q", *spec["paths"]]
    t0 = time.time()
    try:
        rc = subprocess.run(cmd, cwd=REPO, timeout=timeout).returncode
    except subprocess.TimeoutExpired:
        print(f"--- tier {name}: TIMEOUT after {timeout}s", flush=True)
        return False
    print(f"--- tier {name}: {'OK' if rc == 0 else f'FAILED rc={rc}'} "
          f"({time.time() - t0:.0f}s)", flush=True)
    return rc == 0


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--tier", nargs="*", default=None)
    p.add_argument("--list", action="store_true")
    args = p.parse_args()
    matrix = load_matrix()
    if args.list:
        for name, spec in matrix.items():
            print(f"{name}: {spec['description'].strip()}")
        return 0
    names = args.tier or list(matrix)
    failed = [n for n in names if not run_tier(n, matrix[n])]
    if failed:
        print(f"FAILED tiers: {failed}", flush=True)
        return 1
    print("all tiers OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
