#!/bin/bash
# Round perf capture orchestrator: wait out relay outages on the headline
# model, then sweep the control + secondary models in the same healthy
# window. Appends every verbatim result line to $OUT.
OUT=${OUT:-/tmp/round5_captures.jsonl}
# Gate: value present AND not a provisional warmup-window line — provisional
# throughput must not be folded into BENCH_MEASURED.json as a real measurement.
GATE="import json,sys; d=json.load(open(sys.argv[1])); sys.exit(0 if d.get('value') and not d.get('provisional') else 1)"
cd "$(dirname "$0")/.."
try=0
while [ $try -lt 24 ]; do
  try=$((try+1))
  echo "[capture] headline try $try $(date -u +%H:%M)" >&2
  HVD_BENCH_TOTAL_BUDGET_S=1800 timeout 1900 python bench.py \
      > /tmp/cap_headline.json 2>/tmp/cap_headline.log
  if python -c "$GATE" /tmp/cap_headline.json 2>/dev/null; then
    stamp() {  # wrap with the CAPTURE time so provenance survives late merges
      python -c "import json,datetime,sys; print(json.dumps({'measured_at': datetime.datetime.now(datetime.timezone.utc).strftime('%Y-%m-%dT%H:%MZ'), 'result': json.load(open(sys.argv[1]))}))" "$1"
    }
    stamp /tmp/cap_headline.json >> "$OUT"
    echo "[capture] headline OK; sweeping secondaries" >&2
    missing=0
    for model in resnet50_bare bert gpt resnet101 vgg16 inception3; do
      echo "[capture] $model $(date -u +%H:%M)" >&2
      HVD_BENCH_MODEL=$model HVD_BENCH_TOTAL_BUDGET_S=1200 timeout 1300 \
        python bench.py > /tmp/cap_$model.json 2>/tmp/cap_$model.log
      # append only validated, value-carrying JSON (same bar as headline)
      if python -c "$GATE" /tmp/cap_$model.json 2>/dev/null; then
        stamp /tmp/cap_$model.json >> "$OUT"
      else
        echo "[capture] $model FAILED (no valid value)" >&2
        missing=$((missing+1))
      fi
    done
    echo "[capture] DONE ($missing secondaries missing)" >&2
    exit $missing
  fi
  [ $try -lt 24 ] && sleep 300
done
echo "[capture] relay never recovered" >&2
exit 1
